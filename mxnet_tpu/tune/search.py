"""Schedule search: candidate generation, pre-timing pruning, timing.

TVM's lesson (arXiv:1802.04799) applied to the Pallas knob space this
repo already exposes:

- fused conv→BN→ReLU family (``fused_fwd`` / ``fused_wgrad`` /
  ``fused_dgrad``): row-tile, output-channel block, batch fold —
  ``fused_block.mxu_plan`` computes each candidate's per-MXU-call
  multiply-accumulates and ``fused_block.schedule_legal`` its tile
  legality, so illegal and (where the shape can meet the floor at all)
  sub-``MXU_WORK_FLOOR`` candidates are pruned **before** ever being
  timed; the pruning decisions ride the search trajectory in the
  report.
- flash attention (``flash_attention``): ``block_q`` × ``block_k``.

Timing uses the loop-amortized single-jitted-``lax.scan`` harness
(:mod:`.harness`) with round-robin interleaved repeats, so sustained
host drift hits every candidate alike; the trimmed-mean spread per
candidate is reported against the bench_kernel <10% bar. Winners are
committed to the on-disk table (:mod:`.table`); a re-run of a sweep
whose key is already cached is a pure table hit with zero candidate
timings.

**Ranked sweeps (ISSUE 15).** With ``MXNET_TUNE_RANKER=1`` (default)
and a usable learned cost model (:mod:`.model`), a sweep featurizes
every legal candidate, predicts its ms, and times only the
top-``MXNET_TUNE_TOPK`` (the hand default is always timed as the
baseline) — everything else is marked ``skipped_ranked`` with its
predicted ms so the decision rides the trajectory. The ranker
*abstains* (exhaustive sweep, bit-identical to PR 10) when the model
is missing/corrupt, has fewer than ``model.MIN_FIT_ROWS`` rows for
the (kernel, backend) group, or its validation rank correlation is
below ``model.CORR_FLOOR``. Every sweep commit banks ALL its timings
in the table record and — in ranked mode — refits the model, so the
ranker improves across sweeps.
"""
from __future__ import annotations

import itertools
import time

from .table import get_table, make_key

FUSED_KINDS = ("fused_fwd", "fused_wgrad", "fused_dgrad")

# every kernel family sweep_for_key can dispatch — THE one list the
# background tuner's miss filter and the package surface derive from
SWEEPABLE_KERNELS = FUSED_KINDS + ("flash_attention",)

# default candidate grids — the knob space ISSUE 10 names; tune_kernels
# can override per sweep
ROW_TILES = (2, 4, 8, 16, 32)
CHAN_BLOCKS = (64, 128, 256)
BATCH_FOLDS = (1, 2, 4, 8)
FLASH_BLOCKS = (16, 32, 64, 128, 256)


def _axis_values(fixed, *extras):
    """One knob axis's candidate values: the fixed grid (whose
    too-large entries document the pruning at small shapes) plus
    shape-derived values so reduced smoke shapes still have a real
    search space."""
    return tuple(sorted({v for v in tuple(fixed) + tuple(extras)
                         if v and v >= 1}))


SPREAD_BAR_PCT = 10.0


def _mxu_kind(kernel):
    if kernel not in FUSED_KINDS:
        raise ValueError("kernel must be one of %s, got %r"
                         % (FUSED_KINDS, kernel))
    return kernel[len("fused_"):]


def plan_summary(plan):
    """JSON-ready summary of an ``mxu_plan`` result — THE one
    serialization shared by sweep trajectories and bench_kernel's
    per-record plan emission (the join-ability satellite)."""
    return {"grid": list(plan["grid"]), "nb": plan["nb"], "th": plan["th"],
            "bco": plan["bco"], "m": plan["m"], "k": plan["k"],
            "n": plan["n"], "work": plan["work"], "calls": plan["calls"]}


def fused_candidates(kernel, x_shape, w_shape, stride=1, grid=None):
    """Search trajectory for one fused-conv kernel at one shape.

    Returns a list of entries ``{"schedule", "status", ...}`` where
    status is ``default`` (the hand plan, always timed),
    ``candidate`` (eligible for timing), ``pruned_illegal`` (tile >
    dim, non-dividing block, VMEM overrun — with the reason),
    ``pruned_duplicate`` (resolves to an already-listed plan), or
    ``pruned_floor`` (legal but below ``MXU_WORK_FLOOR`` while other
    candidates at this shape meet it). Pure classification — nothing
    here is timed.
    """
    from ..kernels import fused_block as fb

    kind = _mxu_kind(kernel)
    n, h, _wd, ci = x_shape
    co = int(w_shape[-1])
    if grid is None:
        rows = h if kind == "dgrad" else h // stride
        cdim = ci if kind == "dgrad" else co
        grid = [dict(row_tile=rt, chan_block=cb, batch_fold=bf)
                for rt, cb, bf in itertools.product(
                    _axis_values(ROW_TILES, rows, rows // 2),
                    _axis_values(CHAN_BLOCKS, cdim, cdim // 2),
                    _axis_values(BATCH_FOLDS, n))]

    default_plan = fb.mxu_plan(kind, x_shape, w_shape, stride=stride)
    default_sched = dict(row_tile=default_plan["th"],
                         chan_block=default_plan["bco"],
                         batch_fold=default_plan["nb"])
    seen = {(default_plan["th"], default_plan["bco"], default_plan["nb"])}
    entries = [{"schedule": default_sched, "status": "default",
                "work": default_plan["work"],
                "plan": plan_summary(default_plan)}]

    legal = []
    for cand in grid:
        ok, reason = fb.schedule_legal(kind, x_shape, w_shape, stride, cand)
        if not ok:
            entries.append({"schedule": dict(cand),
                            "status": "pruned_illegal", "reason": reason})
            continue
        plan = fb.mxu_plan(kind, x_shape, w_shape, stride=stride,
                           schedule=cand)
        sig = (plan["th"], plan["bco"], plan["nb"])
        entry = {"schedule": dict(cand), "work": plan["work"],
                 "plan": plan_summary(plan)}
        if sig in seen:
            entry["status"] = "pruned_duplicate"
        else:
            seen.add(sig)
            entry["status"] = "candidate"
            legal.append(entry)
        entries.append(entry)

    # floor pruning only when the shape can meet the floor at all —
    # the tiny CPU smoke shapes never do, and pruning everything would
    # leave nothing to search
    ceiling = max((e["work"] for e in legal), default=0)
    if ceiling >= fb.MXU_WORK_FLOOR:
        for e in legal:
            if e["work"] < fb.MXU_WORK_FLOOR:
                e["status"] = "pruned_floor"
    return entries


def flash_candidates(seq_q, seq_k, blocks=None):
    """Search trajectory for flash attention block sizes. Blocks are
    clamped/rounded exactly the way ``flash_attention`` does, so two
    grid points resolving to the same effective pair dedupe; a block
    larger than the clamped sequence is illegal (it would clamp into
    another candidate's program). Decode shapes (ISSUE 12: seq_q == 1)
    collapse every fixed-grid block_q to 1, so the smallest LEGAL
    block per axis joins the grid — a decode sweep then searches the
    block_k axis at block_q == 1 instead of pruning everything."""
    from ..kernels.flash_attention import effective_blocks

    if blocks is None:
        # the smallest legal block per axis: 16 at normal shapes
        # (already on the grid), the exact sequence below the 16-row
        # tile — where every fixed-grid block clamps to it
        min_bq = seq_q if 0 < seq_q < 16 else 16
        min_bk = seq_k if 0 < seq_k < 16 else 16
        blocks = [dict(block_q=bq, block_k=bk)
                  for bq, bk in itertools.product(
                      _axis_values(FLASH_BLOCKS, min_bq),
                      _axis_values(FLASH_BLOCKS, min_bk))]
    default_bq, default_bk = effective_blocks(128, 128, seq_q, seq_k)
    seen = {(default_bq, default_bk)}
    entries = [{"schedule": dict(block_q=default_bq, block_k=default_bk),
                "status": "default"}]
    for cand in blocks:
        bq, bk = cand["block_q"], cand["block_k"]
        entry = {"schedule": dict(cand)}
        ebq, ebk = effective_blocks(bq, bk, seq_q, seq_k)
        if (bq, bk) != (ebq, ebk):
            entry["status"] = "pruned_illegal"
            entry["reason"] = ("blocks (%d, %d) clamp to (%d, %d) at "
                               "seq (%d, %d)" % (bq, bk, ebq, ebk,
                                                 seq_q, seq_k))
        elif (bq, bk) in seen:
            entry["status"] = "pruned_duplicate"
        else:
            seen.add((bq, bk))
            entry["status"] = "candidate"
        entries.append(entry)
    return entries


# ---------------------------------------------------------------------------
# ranked mode (ISSUE 15)
# ---------------------------------------------------------------------------
def _resolve_ranker(ranked, topk):
    """Resolve the ranked-mode knobs: explicit args beat
    ``MXNET_TUNE_RANKER`` / ``MXNET_TUNE_TOPK`` (strict accessors —
    malformed values raise naming the knob)."""
    from .. import config

    if ranked is None:
        ranked = config.get_strict_bool("MXNET_TUNE_RANKER")
    if topk is None:
        topk = config.get_positive_int("MXNET_TUNE_TOPK")
    return bool(ranked), int(topk)


def _apply_ranking(kernel, shape, dtype, backend, entries, topk, table,
                   cost_model=None):
    """Rank the legal candidates with the learned cost model and mark
    everything below the top-``topk`` as ``skipped_ranked`` (predicted
    ms annotated on every scored entry). Returns the ranker report for
    the sweep: ``mode`` is ``ranked`` or — when the model is missing,
    under-trained, or below the validation-correlation floor —
    ``exhaustive`` with ``abstained`` and the reason (behaviorally
    identical to the PR 10 sweep)."""
    import numpy as np

    from . import model as cost_model_mod
    from .. import profiler

    m = cost_model or cost_model_mod.get_model(
        cost_model_mod.model_path_for(table))
    cands = [e for e in entries if e["status"] == "candidate"]
    if not cands:
        # nothing to rank (every candidate pruned / deduped into the
        # default): vacuous ranked mode — the sweep times the default
        # only, exactly like exhaustive would
        return {"mode": "ranked", "abstained": False, "topk": topk,
                "n_scored": 0, "n_skipped": 0,
                "group": cost_model_mod.group_key(kernel, backend),
                "val_corr": None}
    ok, why = m.usable(kernel, backend)
    if not ok:
        profiler.tuning_record(ranker_abstains=1)
        return {"mode": "exhaustive", "abstained": True, "reason": why}
    plans = [e.get("plan") or cost_model_mod.plan_for(kernel, shape,
                                                      e["schedule"])
             for e in cands]
    pred = m.predict(kernel, backend, plans)
    order = np.argsort(pred, kind="mergesort")
    keep = set(int(i) for i in order[:topk])
    skipped = 0
    for i, e in enumerate(cands):
        e["predicted_ms"] = round(float(pred[i]), 6)
        if i not in keep:
            e["status"] = "skipped_ranked"
            skipped += 1
    profiler.tuning_record(candidates_ranked=len(cands),
                           timings_skipped=skipped)
    return {"mode": "ranked", "abstained": False, "topk": topk,
            "n_scored": len(cands), "n_skipped": skipped,
            "group": cost_model_mod.group_key(kernel, backend),
            "val_corr": (m.group(kernel, backend) or {}).get("val_corr")}


def sweep_for_key(kernel, shape, dtype, *, backend=None, **kw):
    """Dispatch a sweep from a table-key ``(kernel, shape, dtype)`` —
    the background tuner's entry point: a recorded miss carries
    exactly these, so the shapes a job traced are directly
    sweepable."""
    shape = tuple(int(d) for d in shape)
    if kernel in FUSED_KINDS:
        n, h, wd, ci, co, k, stride = shape
        return sweep_fused(kernel, (n, h, wd, ci), (k, k, ci, co),
                           stride=stride, dtype=dtype, backend=backend,
                           **kw)
    if kernel == "flash_attention":
        b, h, sq, sk, d, causal = shape
        return sweep_flash(b, h, sq, sk, d, causal=bool(causal),
                           dtype=dtype, backend=backend, **kw)
    raise ValueError("no sweep recipe for kernel %r" % (kernel,))


# ---------------------------------------------------------------------------
# timing + commit
# ---------------------------------------------------------------------------
def _rand(key, shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.random.normal(key, shape, jnp.float32).astype(dtype)


def _time_entries(entries, build_fn, budget, repeats, iters, target_sec,
                  min_iters):
    """Prepare + round-robin time the default entry and up to
    ``budget - 1`` searched candidates; annotates entries in place with
    ms/spread (or ``skipped_budget``) and returns the timed entries.

    Budget truncation orders survivors by the model's prediction
    (ascending) when every survivor carries one — a ranked sweep whose
    budget is tighter than its topk (the background tuner's
    BG_BUDGET=2 vs TOPK=3) must time the predicted-BEST candidates,
    not override the ranking. Exhaustive-mode truncation orders by
    DESCENDING per-call work (flash: block area) — the generation grid
    is ascending, so a naive head-slice would only ever explore the
    smallest-tile corner of the space and, since re-runs are cache
    hits, never reach the likely-good large tiles at all."""
    from . import harness

    searched = [e for e in entries if e["status"] == "candidate"]
    if searched and all("predicted_ms" in e for e in searched):
        searched.sort(key=lambda e: e["predicted_ms"])
    else:
        searched.sort(key=lambda e: -(e.get("work")
                                      or e["schedule"].get("block_q", 1)
                                      * e["schedule"].get("block_k", 1)))
    keep = max(0, budget - 1)
    for e in searched[keep:]:
        e["status"] = "skipped_budget"
    timed = [e for e in entries if e["status"] == "default"] \
        + searched[:keep]

    prepared = []
    for idx, e in enumerate(timed):
        fn, operands = build_fn(e["schedule"])
        run, x0, rest, it = harness.prepare_run(
            fn, operands, iters, target_sec=target_sec,
            min_iters=min_iters)
        prepared.append((idx, run, x0, rest, it))
    runs = harness.time_round_robin(prepared, repeats)
    for idx, e in enumerate(timed):
        mean, spread = harness.summarize(runs[idx])
        e["ms_per_iter"] = round(mean, 5)
        e["spread_pct"] = round(spread * 100, 2)
        e["spread_ok"] = spread * 100 <= SPREAD_BAR_PCT
        e["status"] = "timed" if e["status"] != "default" else "default"
        e["runs_ms"] = [round(r, 5) for r in runs[idx]]
    return timed


def _finish_sweep(kernel, shape, dtype, backend, entries, timed, table,
                  t_start=None, rank_info=None, refit=False):
    from . import model as cost_model_mod

    default = next(e for e in timed if e["status"] == "default")
    winner = min(timed, key=lambda e: e["ms_per_iter"])
    rec = {
        "schedule": dict(winner["schedule"]),
        "ms_per_iter": winner["ms_per_iter"],
        "spread_pct": winner["spread_pct"],
        "default_schedule": dict(default["schedule"]),
        "default_ms_per_iter": default["ms_per_iter"],
        "speedup_vs_default": round(
            default["ms_per_iter"] / winner["ms_per_iter"], 3)
        if winner["ms_per_iter"] else 1.0,
        # bank EVERY timing (ISSUE 15): these are the cost model's
        # training rows — each carries the plan_summary featurization
        # joins on, so table entries, bench records, and model inputs
        # share one representation
        "timings": [
            {"schedule": dict(e["schedule"]),
             "ms_per_iter": e["ms_per_iter"],
             "plan": e.get("plan") or cost_model_mod.plan_for(
                 kernel, shape, e["schedule"])}
            for e in timed],
    }
    # the banked-rows merge (a topk-bounded ranked sweep or background
    # slot must GROW the model's training set, never shrink a previous
    # exhaustive sweep's bank) happens inside table.record, against
    # the merge base re-read from disk at commit time — a concurrent
    # process's rows banked for this key during the sweep survive
    table.record(kernel, shape, dtype, backend, rec)
    rec = table.entry(kernel, shape, dtype, backend) or rec
    report = {
        "key": make_key(kernel, shape, dtype, backend),
        "kernel": kernel, "shape": list(shape), "dtype": dtype,
        "backend": backend, "cache_hit": False,
        "trajectory": entries,
        "n_candidates": len(entries),
        "n_pruned": sum(1 for e in entries
                        if e["status"].startswith("pruned")),
        "n_timed": len(timed),
        "n_skipped_ranked": sum(1 for e in entries
                                if e["status"] == "skipped_ranked"),
        "ranker": rank_info or {"mode": "exhaustive", "abstained": False},
        "winner": rec,
    }
    if refit:
        # the learning loop: every ranked-mode sweep refits the model
        # from the table's accumulated timings, so the ranker improves
        # across sweeps (an under-trained refit just skips groups)
        try:
            fit_rep = cost_model_mod.get_model(
                cost_model_mod.model_path_for(table)).fit_from_table(table)
            report["model_refit"] = fit_rep["fit"]
        except cost_model_mod.CostModelError as e:
            report["model_refit_error"] = str(e)
    if t_start is not None:
        # after the refit: the ranked mode's reported wall-time must
        # carry the refit cost it alone pays — the >=5x acceptance and
        # bench sweep_speedup compare these numbers
        report["wall_s"] = round(time.perf_counter() - t_start, 4)
    return report


def _cache_hit_report(kernel, shape, dtype, backend, table, cached):
    return {"key": make_key(kernel, shape, dtype, backend),
            "kernel": kernel, "shape": list(shape), "dtype": dtype,
            "backend": backend, "cache_hit": True, "n_timed": 0,
            "winner": dict(cached)}


def sweep_fused(kernel, x_shape, w_shape, stride=1, dtype="bfloat16", *,
                budget=8, repeats=5, iters=None, target_sec=0.3,
                min_iters=10, interpret=None, grid=None, table=None,
                force=False, backend=None, ranked=None, topk=None,
                cost_model=None):
    """Search one fused-conv kernel at one shape; commit the winner.

    The cache check goes through :meth:`ScheduleTable.lookup`, so a
    sweep whose key is already tuned is a pure table hit — zero
    candidate timings, visible in ``profiler.tuning_stats``.
    ``ranked``/``topk`` default to the ``MXNET_TUNE_RANKER`` /
    ``MXNET_TUNE_TOPK`` knobs; in ranked mode only the model's
    top-``topk`` candidates (plus the hand default) are timed.
    """
    import jax
    import jax.numpy as jnp

    from ..kernels import fused_block as fb

    if backend is None:
        backend = jax.default_backend()
    table = table if table is not None else get_table()  # empty table is falsy
    n, h, wd, ci = x_shape
    k = int(w_shape[0])
    co = int(w_shape[-1])
    shape = (n, h, wd, ci, co, k, stride)
    if not force:
        cached = table.lookup(kernel, shape, dtype, backend)
        if cached is not None:
            return _cache_hit_report(kernel, shape, dtype, backend, table,
                                     table.entry(kernel, shape, dtype,
                                                 backend))

    t_start = time.perf_counter()
    ranked, topk = _resolve_ranker(ranked, topk)
    entries = fused_candidates(kernel, x_shape, w_shape, stride, grid=grid)
    rank_info = None
    if ranked:
        rank_info = _apply_ranking(kernel, shape, dtype, backend, entries,
                                   topk, table, cost_model)

    jdt = jnp.dtype(dtype)
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    x = _rand(keys[0], tuple(x_shape), jdt)
    w = _rand(keys[1], tuple(w_shape), jdt)
    scale = jax.random.uniform(keys[2], (ci,), jnp.float32, 0.5, 1.5)
    bias = jax.random.normal(keys[3], (ci,), jnp.float32) * 0.1
    ho, wo = h // stride, wd // stride

    def build_fn(sched):
        if kernel == "fused_fwd":
            fn = (lambda x_, w_, s_, b_, _s=dict(sched):
                  fb.conv_fwd(x_, w_, stride=stride,
                              prologue=(s_, b_, True), emit_stats=True,
                              interpret=interpret, schedule=_s))
            return fn, (x, w, scale, bias)
        if kernel == "fused_wgrad":
            g = _rand(keys[1], (n, ho, wo, co), jdt)
            fn = (lambda x_, g_, s_, b_, _s=dict(sched):
                  fb.conv_wgrad(x_, g_, tuple(w_shape), stride=stride,
                                x_prologue=(s_, b_, True),
                                interpret=interpret, schedule=_s))
            return fn, (x, g, scale, bias)
        g = _rand(keys[1], (n, ho, wo, co), jdt)
        fn = (lambda g_, w_, _s=dict(sched):
              fb.conv_dgrad(g_, w_, tuple(x_shape), stride=stride,
                            interpret=interpret, schedule=_s))
        return fn, (g, w)

    timed = _time_entries(entries, build_fn, budget, repeats, iters,
                          target_sec, min_iters)
    return _finish_sweep(kernel, shape, dtype, backend, entries, timed,
                         table, t_start=t_start,
                         rank_info=rank_info, refit=ranked)


def sweep_flash(b, h, seq_q, seq_k, d, causal=False, dtype="float32", *,
                budget=8, repeats=5, iters=None, target_sec=0.3,
                min_iters=10, interpret=None, blocks=None, table=None,
                force=False, backend=None, ranked=None, topk=None,
                cost_model=None):
    """Search flash-attention (block_q, block_k) at one shape; commit
    the winner. Times the forward kernel (backward reuses the same
    block parameters). ``ranked``/``topk`` as in :func:`sweep_fused`."""
    import jax
    import jax.numpy as jnp

    from ..kernels.flash_attention import flash_attention

    if backend is None:
        backend = jax.default_backend()
    table = table if table is not None else get_table()  # empty table is falsy
    shape = (b, h, seq_q, seq_k, d, int(bool(causal)))
    if not force:
        cached = table.lookup("flash_attention", shape, dtype, backend)
        if cached is not None:
            return _cache_hit_report("flash_attention", shape, dtype,
                                     backend, table,
                                     table.entry("flash_attention", shape,
                                                 dtype, backend))

    t_start = time.perf_counter()
    ranked, topk = _resolve_ranker(ranked, topk)
    entries = flash_candidates(seq_q, seq_k, blocks=blocks)
    rank_info = None
    if ranked:
        rank_info = _apply_ranking("flash_attention", shape, dtype,
                                   backend, entries, topk, table,
                                   cost_model)

    jdt = jnp.dtype(dtype)
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = _rand(keys[0], (b, h, seq_q, d), jdt)
    k = _rand(keys[1], (b, h, seq_k, d), jdt)
    v = _rand(keys[2], (b, h, seq_k, d), jdt)

    def build_fn(sched):
        fn = (lambda q_, k_, v_, _s=dict(sched):
              flash_attention(q_, k_, v_, causal=causal,
                              block_q=_s["block_q"], block_k=_s["block_k"],
                              interpret=interpret))
        return fn, (q, k, v)

    timed = _time_entries(entries, build_fn, budget, repeats, iters,
                          target_sec, min_iters)
    return _finish_sweep("flash_attention", shape, dtype, backend, entries,
                         timed, table,
                         t_start=t_start,
                         rank_info=rank_info, refit=ranked)
