"""Runtime kernel compilation (``mx.rtc``).

Reference counterpart: ``python/mxnet/rtc.py`` + ``src/common/rtc.cc`` —
NVRTC-compiled CUDA source strings launched on NDArrays. The TPU-native
equivalent compiles **Python source defining a JAX/Pallas kernel** at
runtime: the source must define a function named like the requested
kernel taking jax arrays; ``get_kernel(...).launch(args, ctx, ...)``
jit-compiles it for the target device (grid/block dims are accepted for
API compatibility and ignored — XLA/Mosaic choose the schedule).
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["CudaModule", "CudaKernel"]


class CudaKernel:
    """A compiled kernel handle (ref rtc.py CudaKernel)."""

    def __init__(self, fn, name):
        import jax

        self._fn = fn
        self._jit = jax.jit(fn)
        self.name = name

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """Run on the NDArray args; returns NDArray(s). grid/block/shared
        accepted for reference API compatibility (XLA schedules)."""
        from .ndarray.ndarray import NDArray

        vals = [a._data() if isinstance(a, NDArray) else a for a in args]
        out = self._jit(*vals)
        if isinstance(out, tuple):
            return tuple(NDArray(o, ctx=ctx) for o in out)
        return NDArray(out, ctx=ctx)


class CudaModule:
    """Compile kernel source at runtime (ref rtc.py CudaModule).

    ``source`` is Python defining one or more kernel functions over jax
    arrays (jnp / jax.lax / pallas all in scope)::

        mod = mx.rtc.CudaModule('''
        def axpy(a, x, y):
            return a * x + y
        ''')
        k = mod.get_kernel("axpy", "")
        out = k.launch([a, x, y], mx.tpu(0))
    """

    def __init__(self, source, options=(), exports=()):
        import jax
        import jax.numpy as jnp

        self._namespace = {"jax": jax, "jnp": jnp, "lax": jax.lax}
        try:
            from jax.experimental import pallas as pl
            from jax.experimental.pallas import tpu as pltpu

            self._namespace["pl"] = pl
            self._namespace["pltpu"] = pltpu
        except ImportError:
            pass
        try:
            exec(compile(source, "<mx.rtc source>", "exec"), self._namespace)
        except SyntaxError as e:
            raise MXNetError("rtc: cannot compile kernel source: %s" % e)
        self._exports = tuple(exports)

    def get_kernel(self, name, signature=""):
        """Fetch a kernel by function name; ``signature`` accepted for
        reference API compatibility (types come from the arrays)."""
        fn = self._namespace.get(name)
        if fn is None or not callable(fn):
            raise MXNetError("rtc: source defines no kernel %r" % name)
        return CudaKernel(fn, name)
