"""Deterministic fault injection for the elastic recovery stack.

The reference MXNet's fault-tolerance tests SIGKILL real processes on a
sleep timer — every recovery path is exercised by a race. This module
replaces that with a *reproducible* harness: ``MXNET_FAULT_SPEC``
describes exactly which process fails, where, and how, and the hooks in
``model.py`` (worker steps), ``kvstore_server.py`` (RPC client/server,
server push application) and ``tracker.py`` (heartbeats) fire the fault
at the same point on every run.

Grammar (semicolon-separated rules)::

    spec   := rule (';' rule)*
    rule   := target '@' params
    target := ('worker'|'server') ':' rank ':' 'crash'
            | 'rpc' ':' 'drop'
            | 'heartbeat' ':' 'stall'
    params := key '=' value (',' key '=' value)*

Rules:

``worker:R:crash@step=N``  /  ``server:R:crash@step=N``
    The matching process hard-exits (``os._exit(137)`` — the SIGKILL
    exit code: no atexit hooks, no ``done`` report, exactly like a real
    preemption) at its N-th step. A worker step is one optimizer-update
    round (``model._update_params*``); a server step is one applied
    ``push``. Crash rules default to ``restart=0`` — they fire only in
    the first incarnation, so a respawned process does not immediately
    re-crash; override with ``restart=K`` or ``restart=any``.

``rpc:drop@op=OP[,p=P,seed=S][,n=N][,phase=send|reply][,side=client|server]``
    Connection drop on a matching kvstore RPC. ``phase=send`` (default)
    drops before the request leaves the client — the server never sees
    it; ``phase=reply`` drops after the request is sent but before the
    reply is read — the op IS applied server-side, so the client's
    retry exercises the sequence-number dedupe. ``side=server`` drops
    the connection server-side before the op is applied. Either ``p``
    (probability, drawn from ``random.Random(seed)`` — same seed, same
    decisions) or ``n`` (fire on the first N matches, deterministic).
    Omitting both means *every* match fires.

``heartbeat:stall@after=N``
    The tracker client stops sending heartbeats after the N-th — the
    wedged-process simulation (sockets stay open, beats stop), which is
    exactly what the scheduler's heartbeat timeout exists to catch.

``worker:R:nan@step=N`` (ISSUE 9 fault matrix)
    The matching worker's N-th optimizer round runs with a poisoned
    gradient: the per-executor tiers overwrite ONE gradient array with
    NaN before the update/push, the fused tier poisons the step's data
    batch so the whole compiled step's gradients go non-finite — the
    *silent* fault the in-graph sentinel and the fit health guard
    exist to catch. Fires once per incarnation (``restart`` gating as
    for crash).

``worker:R:preempt@step=N``  /  ``server:R:preempt@step=N``
    The matching process sends SIGTERM to itself at its N-th step —
    the scheduler-preemption simulation. With the preemption handler
    installed (launch.py-spawned workers, mxnet_tpu/health.py) the
    process drains, checkpoints inside ``MXNET_PREEMPT_GRACE`` and
    exits with the resumable ``EXIT_PREEMPTED`` status; without it the
    default SIGTERM disposition kills the process like a crash.

``replica:R:crash@req=N``  /  ``replica:R:stall@req=N`` (ISSUE 11)
    Serving-fleet faults, counted in ADMITTED REQUESTS (a replica has
    no training steps). ``crash`` hard-exits (``os._exit(137)``) at
    the N-th admitted request — the replica-SIGKILL simulation the
    router's retry/failover path exists for. ``stall`` wedges request
    serving from the N-th request ON (the handler blocks; sockets and
    heartbeats stay open) — the wedged-but-alive replica only the
    router's per-attempt deadline catches. ``crash`` fires once per
    incarnation (``restart`` gating as for worker crash); ``stall``
    defaults to ``restart=any``.

``generate:stall@req=N`` (ISSUE 12)
    The N-th ADMITTED generate request never emits EOS — the
    wedged-generation simulation (a client streaming forever, a model
    that never produces the stop token): the request's EOS check is
    suppressed so only the ``MXNET_GENERATE_MAX_STEPS`` cap (or its
    deadline) can finish it. The reaction under test: the cap fires,
    the request finishes with reason ``length``, and its batch slot +
    KV pages are reclaimed for the requests queued behind it. Fires
    once (``restart`` gating defaults to ``any`` — the serving loop
    has no incarnations).

``autoscaler:crash@tick=N`` (ISSUE 18)
    The fleet's autoscale controller hard-exits (``os._exit(137)``) at
    its N-th control tick — the dead-controller simulation behind the
    fail-static contract: replicas keep serving, the router keeps
    routing, and the launcher keeps supervising at the fleet's current
    size; only *scaling* stops. Counted in controller ticks (the
    controller has neither steps nor requests); ``restart`` gating
    defaults to ``any`` (the controller is not launcher-supervised).

``router:drop@[p=P,seed=S|n=N][,phase=send|reply]`` (ISSUE 11)
    Connection drop on a matching router→replica forward.
    ``phase=send`` (default) drops BEFORE the request leaves the
    router — a never-sent request, retry-safe on any replica
    regardless of idempotency; ``phase=reply`` drops AFTER the request
    was delivered but before the reply is read — an in-flight loss,
    which the router must fail distinctly (``ReplicaConnectionLost``)
    and retry only for idempotent requests.

A malformed spec raises :class:`FaultSpecError` at parse time — a chaos
harness that silently no-ops would certify recovery paths that were
never exercised.
"""
from __future__ import annotations

import os
import random
import signal
import sys

_EXIT_CODE = 137  # SIGKILL'd processes report 128+9; crash mimics that

_TARGETS = ("worker", "server", "replica", "rpc", "router", "heartbeat",
            "generate", "autoscaler")
_ACTIONS = {"worker": ("crash", "nan", "preempt"),
            "server": ("crash", "preempt"),
            "replica": ("crash", "stall"),
            "rpc": ("drop",), "router": ("drop",),
            "heartbeat": ("stall",),
            "generate": ("stall",),
            "autoscaler": ("crash",)}


class FaultSpecError(ValueError):
    """MXNET_FAULT_SPEC could not be parsed (or is inconsistent)."""


def _parse_int(rule_text, key, value):
    try:
        return int(value)
    except ValueError:
        raise FaultSpecError(
            "fault rule %r: %s=%r is not an integer" % (rule_text, key, value))


class _Rule:
    __slots__ = ("text", "target", "rank", "action", "params", "rng",
                 "fired", "matched")

    def __init__(self, text):
        self.text = text
        self.fired = 0
        self.matched = 0
        head, sep, tail = text.partition("@")
        if not sep or not tail:
            raise FaultSpecError(
                "fault rule %r: expected '<target>@<k=v,...>'" % text)
        parts = head.split(":")
        if parts[0] not in _TARGETS:
            raise FaultSpecError(
                "fault rule %r: unknown target %r (expected one of %s)"
                % (text, parts[0], "/".join(_TARGETS)))
        self.target = parts[0]
        if self.target in ("worker", "server", "replica"):
            if len(parts) != 3:
                raise FaultSpecError(
                    "fault rule %r: expected '%s:<rank>:<action>@...'"
                    % (text, self.target))
            self.rank = _parse_int(text, "rank", parts[1])
            self.action = parts[2]
        else:
            if len(parts) != 2:
                raise FaultSpecError(
                    "fault rule %r: expected '%s:<action>@...'"
                    % (text, self.target))
            self.rank = None
            self.action = parts[1]
        if self.action not in _ACTIONS[self.target]:
            raise FaultSpecError(
                "fault rule %r: target %r supports actions %s, got %r"
                % (text, self.target, "/".join(_ACTIONS[self.target]),
                   self.action))
        self.params = {}
        for kv in tail.split(","):
            k, sep, v = kv.partition("=")
            if not sep or not k:
                raise FaultSpecError(
                    "fault rule %r: bad parameter %r (expected k=v)"
                    % (text, kv))
            self.params[k.strip()] = v.strip()
        self._validate()
        p = self.params.get("p")
        self.rng = random.Random(_parse_int(text, "seed",
                                            self.params.get("seed", "0"))) \
            if p is not None else None

    def _validate(self):
        p = self.params
        if self.target in ("replica", "generate"):
            # replica/generate faults count admitted requests, not
            # train steps
            if "req" not in p:
                raise FaultSpecError(
                    "fault rule %r: %s %s requires req=N"
                    % (self.text, self.target, self.action))
        elif self.target == "autoscaler":
            # autoscaler faults count control ticks — the controller
            # has neither train steps nor admitted requests
            if "tick" not in p:
                raise FaultSpecError(
                    "fault rule %r: autoscaler crash requires tick=N"
                    % self.text)
        elif self.action in ("crash", "nan", "preempt") and "step" not in p:
            raise FaultSpecError(
                "fault rule %r: %s requires step=N"
                % (self.text, self.action))
        if self.target == "heartbeat" and "after" not in p:
            raise FaultSpecError(
                "fault rule %r: stall requires after=N" % self.text)
        if self.target == "router":
            for bad in ("op", "side"):
                if bad in p:
                    raise FaultSpecError(
                        "fault rule %r: %s only applies to rpc rules "
                        "(the router drop always targets the "
                        "router→replica forward)" % (self.text, bad))
        for key in ("step", "after", "req", "n", "seed", "tick"):
            if key in p:
                _parse_int(self.text, key, p[key])
        if "p" in p:
            try:
                prob = float(p["p"])
            except ValueError:
                raise FaultSpecError(
                    "fault rule %r: p=%r is not a float"
                    % (self.text, p["p"]))
            if not 0.0 <= prob <= 1.0:
                raise FaultSpecError(
                    "fault rule %r: p=%s out of [0, 1]" % (self.text, prob))
        if p.get("phase", "send") not in ("send", "reply"):
            raise FaultSpecError(
                "fault rule %r: phase must be send|reply" % self.text)
        if p.get("side", "client") not in ("client", "server"):
            raise FaultSpecError(
                "fault rule %r: side must be client|server" % self.text)
        if p.get("side") == "server" and "phase" in p:
            # the server hook fires before the op is applied — there is
            # no reply phase there; silently ignoring the param would
            # certify a recovery path that was never exercised
            raise FaultSpecError(
                "fault rule %r: phase only applies to side=client "
                "(the server-side drop always happens before the op "
                "is applied)" % self.text)
        restart = p.get("restart")
        if restart is not None and restart != "any":
            _parse_int(self.text, "restart", restart)

    # -- matching ------------------------------------------------------------
    def restart_matches(self, restart, default="0"):
        want = self.params.get("restart", default)
        if want == "any":
            return True
        return int(want) == restart

    def should_fire(self):
        """Count/probability gate shared by rpc/heartbeat rules; call
        only after the structural match succeeded."""
        self.matched += 1
        if "n" in self.params:
            return self.matched <= int(self.params["n"])
        if self.rng is not None:
            return self.rng.random() < float(self.params["p"])
        return True


def parse_spec(text):
    """MXNET_FAULT_SPEC text -> [_Rule]. Raises FaultSpecError."""
    rules = []
    for chunk in (text or "").split(";"):
        chunk = chunk.strip()
        if chunk:
            rules.append(_Rule(chunk))
    return rules


class ChaosEngine:
    """One process's view of the fault spec: knows its own role, rank
    and incarnation (restart count), counts its steps, and fires the
    matching rules at the configured points."""

    def __init__(self, spec, role=None, rank=None, restart=None):
        self.rules = parse_spec(spec)
        self.role = role if role is not None else \
            os.environ.get("DMLC_ROLE", "worker").lower()
        if rank is None:
            if self.role == "server":
                rank = os.environ.get("DMLC_SERVER_ID", "0")
            elif self.role == "replica":
                rank = os.environ.get("DMLC_REPLICA_ID", "0")
            else:
                rank = (os.environ.get("DMLC_WORKER_ID")
                        or os.environ.get("DMLC_RANK")
                        or os.environ.get("MXNET_TPU_WORKER_ID") or "0")
        self.rank = int(rank or 0)
        if restart is None:
            restart = os.environ.get("DMLC_RESTART_COUNT", "0")
        self.restart = int(restart or 0)
        self._step = 0
        self._beats = 0
        self._reqs = 0
        self._gen_reqs = 0
        self._as_ticks = 0
        self._exit = os._exit  # injectable for tests
        self._kill = lambda: os.kill(os.getpid(), signal.SIGTERM)  # ditto

    def _crash(self, rule):
        print("[chaos] injecting crash: rule %r fired at %s %d step %d "
              "(restart %d)" % (rule.text, self.role, self.rank,
                                self._step, self.restart),
              file=sys.stderr, flush=True)
        sys.stderr.flush()
        self._exit(_EXIT_CODE)

    def _preempt(self, rule):
        print("[chaos] injecting preemption (SIGTERM to self): rule %r "
              "fired at %s %d step %d (restart %d)"
              % (rule.text, self.role, self.rank, self._step,
                 self.restart), file=sys.stderr, flush=True)
        sys.stderr.flush()
        self._kill()

    def _match_step_rule(self, rule, action, step):
        return (rule.action == action and rule.target == self.role
                and rule.rank == self.rank
                and rule.restart_matches(self.restart)
                and step == int(rule.params["step"])
                and not rule.fired)

    def step(self):
        """One unit of progress (worker: optimizer round; server:
        applied push). Fires crash/preempt rules scheduled for this
        step."""
        self._step += 1
        for rule in self.rules:
            if self._match_step_rule(rule, "crash", self._step):
                rule.fired += 1
                self._crash(rule)
            elif self._match_step_rule(rule, "preempt", self._step):
                rule.fired += 1
                self._preempt(rule)

    def nan(self):
        """True when the round ABOUT to run matches a nan rule. Callers
        check before their ``tick_step()`` for the round (the gradient
        must be poisoned before the update/push consumes it), so this
        matches against ``step + 1``."""
        nxt = self._step + 1
        for rule in self.rules:
            if self._match_step_rule(rule, "nan", nxt):
                rule.fired += 1
                print("[chaos] poisoning gradient with NaN: rule %r "
                      "fired at %s %d step %d (restart %d)"
                      % (rule.text, self.role, self.rank, nxt,
                         self.restart), file=sys.stderr, flush=True)
                return True
        return False

    def replica_request(self):
        """Count one admitted serving request; fire matching replica
        rules. Returns ``"stall"`` when the handler must wedge (serve
        nothing, keep the socket open), None otherwise; a matching
        crash rule never returns."""
        self._reqs += 1
        for rule in self.rules:
            if (rule.target != "replica" or rule.rank != self.rank
                    or self.role != "replica"):
                continue
            if rule.action == "crash" \
                    and rule.restart_matches(self.restart) \
                    and self._reqs == int(rule.params["req"]) \
                    and not rule.fired:
                rule.fired += 1
                self._step = self._reqs  # the crash log names a "step"
                self._crash(rule)
            elif rule.action == "stall" \
                    and rule.restart_matches(self.restart, default="any") \
                    and self._reqs >= int(rule.params["req"]):
                if not rule.fired:
                    rule.fired += 1
                    print("[chaos] wedging replica (stall): rule %r "
                          "fired at replica %d request %d (restart %d)"
                          % (rule.text, self.rank, self._reqs,
                             self.restart), file=sys.stderr, flush=True)
                return "stall"
        return None

    def generate_request(self):
        """Count one admitted generate request; returns ``"stall"``
        when this request must never emit EOS (generate:stall@req=N —
        only the max-decode-steps cap or its deadline can finish it),
        None otherwise. Role/rank-free: the generate loop runs inside
        whatever serving process hosts it."""
        self._gen_reqs += 1
        for rule in self.rules:
            if rule.target != "generate" or rule.action != "stall":
                continue
            if not rule.restart_matches(self.restart, default="any"):
                continue
            if self._gen_reqs == int(rule.params["req"]) \
                    and not rule.fired:
                rule.fired += 1
                print("[chaos] suppressing EOS (generate stall): rule "
                      "%r fired at generate request %d"
                      % (rule.text, self._gen_reqs),
                      file=sys.stderr, flush=True)
                return "stall"
        return None

    def autoscaler_tick(self):
        """Count one autoscaler control tick; a matching
        ``autoscaler:crash@tick=N`` rule hard-exits the controller —
        the dead-controller half of the fail-static contract.
        Role/rank-free: the controller runs outside the launcher's
        role topology (``restart`` gating defaults to ``any``)."""
        self._as_ticks += 1
        for rule in self.rules:
            if rule.target != "autoscaler" or rule.action != "crash":
                continue
            if not rule.restart_matches(self.restart, default="any"):
                continue
            if self._as_ticks == int(rule.params["tick"]) \
                    and not rule.fired:
                rule.fired += 1
                self._step = self._as_ticks  # the crash log's "step"
                self._crash(rule)

    def router_drop(self, phase="send"):
        """True when a matching router:drop rule fires for this
        router→replica forward attempt."""
        for rule in self.rules:
            if rule.target != "router" or rule.action != "drop":
                continue
            if not rule.restart_matches(self.restart, default="any"):
                continue
            if rule.params.get("phase", "send") != phase:
                continue
            if rule.should_fire():
                print("[chaos] dropping router forward (%s) per rule %r"
                      % (phase, rule.text), file=sys.stderr, flush=True)
                return True
        return False

    def rpc(self, op, phase="send", side="client"):
        """True when a matching rpc:drop rule fires for this op."""
        for rule in self.rules:
            if rule.target != "rpc" or rule.action != "drop":
                continue
            if not rule.restart_matches(self.restart, default="any"):
                continue
            want_op = rule.params.get("op")
            if want_op is not None and want_op != op:
                continue
            if rule.params.get("side", "client") != side:
                continue
            if side == "client" and rule.params.get("phase", "send") != phase:
                continue
            if rule.should_fire():
                print("[chaos] dropping rpc %r (%s/%s) per rule %r"
                      % (op, side, phase, rule.text),
                      file=sys.stderr, flush=True)
                return True
        return False

    def heartbeat(self):
        """True when the heartbeat should be suppressed (stall rule)."""
        self._beats += 1
        for rule in self.rules:
            if (rule.target == "heartbeat" and rule.action == "stall"
                    and rule.restart_matches(self.restart, default="any")
                    and self._beats > int(rule.params["after"])):
                return True
        return False


# ---------------------------------------------------------------------------
# process-wide engine (env-driven), with cheap no-op fast path
# ---------------------------------------------------------------------------
_UNSET = object()
_ENGINE = _UNSET


def engine():
    """The process's ChaosEngine, parsed once from MXNET_FAULT_SPEC;
    None when the env var is unset/empty (the common case — every hook
    is then a single attribute check)."""
    global _ENGINE
    if _ENGINE is _UNSET:
        spec = os.environ.get("MXNET_FAULT_SPEC", "").strip()
        _ENGINE = ChaosEngine(spec) if spec else None
    return _ENGINE


def reset_engine():
    """Forget the cached engine (tests that monkeypatch the env)."""
    global _ENGINE
    _ENGINE = _UNSET


def tick_step():
    e = engine()
    if e is not None:
        e.step()


def nan_fault():
    """True when the upcoming optimizer round should run with a
    poisoned gradient (worker:R:nan@step=N). Call BEFORE tick_step()."""
    e = engine()
    return e is not None and e.nan()


def rpc_fault(op, phase="send", side="client"):
    e = engine()
    return e is not None and e.rpc(op, phase=phase, side=side)


def replica_request_fault():
    """Per-admitted-request replica hook (serving/fleet.py): returns
    ``"stall"`` to wedge the handler, None otherwise; a matching crash
    rule hard-exits the process."""
    e = engine()
    return e.replica_request() if e is not None else None


def generate_fault():
    """Per-admitted-generate-request hook (serving/broker.py
    GenerateServer): returns ``"stall"`` when the request must never
    emit EOS, None otherwise."""
    e = engine()
    return e.generate_request() if e is not None else None


def autoscaler_fault():
    """Per-control-tick autoscaler hook (serving/autoscale.py): a
    matching ``autoscaler:crash@tick=N`` rule hard-exits the
    controller process and never returns."""
    e = engine()
    if e is not None:
        e.autoscaler_tick()


def router_fault(phase="send"):
    """True when the router must drop this forward attempt
    (router:drop rule; phase=send before the request leaves, reply
    after it was delivered)."""
    e = engine()
    return e is not None and e.router_drop(phase=phase)


def heartbeat_fault():
    e = engine()
    return e is not None and e.heartbeat()
