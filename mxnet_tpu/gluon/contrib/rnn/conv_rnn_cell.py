"""Convolutional RNN cells (ref: python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py)."""
from __future__ import annotations

from ....base import MXNetError
from ....ndarray.ndarray import invoke
from ...rnn.rnn_cell import RecurrentCell


class Conv2DLSTMCell(RecurrentCell):
    """ConvLSTM (Shi et al. 2015; ref conv_rnn_cell.py Conv2DLSTMCell)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=(0, 0), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)  # (C, H, W)
        self._hidden_channels = hidden_channels
        self._i2h_kernel = (i2h_kernel,) * 2 if isinstance(i2h_kernel, int) else tuple(i2h_kernel)
        self._h2h_kernel = (h2h_kernel,) * 2 if isinstance(h2h_kernel, int) else tuple(h2h_kernel)
        self._i2h_pad = (i2h_pad,) * 2 if isinstance(i2h_pad, int) else tuple(i2h_pad)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)
        ci = self._input_shape[0]
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_channels, ci) + self._i2h_kernel,
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_channels, hidden_channels) + self._h2h_kernel,
                allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_channels,), init="zeros", allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_channels,), init="zeros", allow_deferred_init=True)

    def state_info(self, batch_size=0):
        c, h, w = self._input_shape
        oh = h + 2 * self._i2h_pad[0] - self._i2h_kernel[0] + 1
        ow = w + 2 * self._i2h_pad[1] - self._i2h_kernel[1] + 1
        shape = (batch_size, self._hidden_channels, oh, ow)
        return [{"shape": shape, "__layout__": "NCHW"}, {"shape": shape, "__layout__": "NCHW"}]

    def _alias(self):
        return "conv_lstm"

    def step(self, inputs, states):
        for p in self._reg_params.values():
            if p._data is None:
                p._finish_deferred_init()
        i2h = invoke("Convolution", [inputs, self.i2h_weight.data(), self.i2h_bias.data()],
                     {"kernel": self._i2h_kernel, "pad": self._i2h_pad,
                      "num_filter": 4 * self._hidden_channels})
        h2h = invoke("Convolution", [states[0], self.h2h_weight.data(), self.h2h_bias.data()],
                     {"kernel": self._h2h_kernel, "pad": self._h2h_pad,
                      "num_filter": 4 * self._hidden_channels})
        gates = i2h + h2h
        slices = invoke("SliceChannel", [gates], {"num_outputs": 4, "axis": 1})
        i = slices[0].sigmoid()
        f = slices[1].sigmoid()
        g = slices[2].tanh()
        o = slices[3].sigmoid()
        next_c = f * states[1] + i * g
        next_h = o * next_c.tanh()
        return next_h, [next_h, next_c]
