"""Contrib RNN cells (ref: python/mxnet/gluon/contrib/rnn/)."""
from .conv_rnn_cell import Conv2DLSTMCell  # noqa: F401
from .rnn_cell import VariationalDropoutCell  # noqa: F401
