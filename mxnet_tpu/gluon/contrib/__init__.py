"""Gluon contrib (ref: python/mxnet/gluon/contrib/ — Conv*RNN cells,
VariationalDropoutCell). Populated as the RNN contrib surface lands."""
from . import rnn  # noqa: F401
