"""Gluon Block / HybridBlock / SymbolBlock.

Reference counterpart: ``python/mxnet/gluon/block.py`` (Block :121,
HybridBlock deferred init + _build_cache → CachedOp :381-384, hybridize
:443, SymbolBlock :542). TPU-native design: ``hybridize()`` compiles the
block's computation into ONE jitted XLA function (the CachedOp analogue,
ref src/imperative/cached_op.cc) keyed on input shapes/dtypes; parameters
are passed as traced arguments so optimizer updates need no re-trace, and a
fresh PRNG key is threaded per call for dropout parity.
"""
from __future__ import annotations

import re
import threading

from .. import autograd
from ..base import MXNetError, auto_name
from ..context import current_context
from ..ndarray import ndarray as nd
from ..ndarray.ndarray import NDArray, _wrap_raw
from .parameter import DeferredInitializationError, Parameter, ParameterDict

_naming = threading.local()


class _BlockScope:
    """Name scoping for parameter prefixes (ref: block.py _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = auto_name(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class Block:
    """Base building block (ref: block.py:121)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            "  ({key}): {block}".format(key=key, block=_indent(repr(block), 2))
            for key, block in self._children.items()
        )
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(value, type(existing)):
                raise TypeError(
                    "Changing attribute type for %s from %s to %s is not allowed."
                    % (name, type(existing), type(value))
                )
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or self._reg_params[name] is value, (
                "Overriding Parameter attribute %s is not allowed." % name
            )
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _check_container_with_block(self):
        pass

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items() if pattern.match(name)})
        for cld in self._children.values():
            ret.update(cld.collect_params(select=select))
        return ret

    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    save_parameters = save_params

    def load_params(self, filename, ctx=None, allow_missing=False, ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing, ignore_extra, self.prefix)

    load_parameters = load_params

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        raise NotImplementedError


def _indent(s_, num_spaces):
    lines = s_.split("\n")
    if len(lines) == 1:
        return s_
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


class HybridBlock(Block):
    """Block compilable into one XLA program (ref: block.py HybridBlock)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_fn = None
        self._cache_key = None
        self._flags = {}

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = kwargs
        self._clear_cached_op()
        super().hybridize(active, **kwargs)

    def _clear_cached_op(self):
        self._cached_fn = None
        self._cache_key = None

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, but %s has type %s."
                % (str(block), str(type(block)))
            )
        super().register_child(block, name)
        self._clear_cached_op()

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Deferred-shape resolution by abstract evaluation."""
        self._deferred_infer(args)

    def _deferred_infer(self, args):
        # run an eager forward with params replaced by zeros once shapes known
        pass

    def __call__(self, *args):
        self._num_inputs = len(args)  # remembered for export()
        if self._active:
            out = self._call_cached(args)
            for hook in self._forward_hooks:
                hook(self, args, out)
            return out
        return super().__call__(*args)

    def export(self, path, epoch=0):
        """Export the inference graph + params for deployment (ref:
        block.py HybridBlock.export — emits ``path-symbol.json`` and
        ``path-%04d.params``, loadable by SymbolBlock / Module / the
        reference's C predict API surface).

        Requires initialized params (run a forward once first). The
        forward is re-traced with Symbol placeholders, so blocks whose
        forward inspects concrete shapes cannot be exported.
        """
        from .. import symbol as symmod
        from ..ndarray import utils as nd_utils

        params = self._collect_all_reg_params()
        for p in params.values():
            p.data()  # raises for uninitialized/deferred params
        n = getattr(self, "_num_inputs", 1)
        ins = [symmod.var("data" if n == 1 else "data%d" % i)
               for i in range(n)]
        # disable hybrid caching during the symbolic trace
        saved = {}

        def walk(b):
            if isinstance(b, HybridBlock):
                saved[b] = b._active
                b._active = False
            for c in b._children.values():
                walk(c)

        walk(self)
        try:
            out = self.forward(*ins)
        finally:
            for b, a in saved.items():
                b._active = a
        if isinstance(out, (list, tuple)):
            out = symmod.Group(list(out))
        out.save("%s-symbol.json" % path)
        arg_names = set(out.list_arguments())
        aux_names = set(out.list_auxiliary_states())
        save_dict = {}
        for name, p in params.items():
            if name in arg_names:
                save_dict["arg:%s" % name] = p.data()
            elif name in aux_names:
                save_dict["aux:%s" % name] = p.data()
        nd_utils.save("%s-%04d.params" % (path, epoch), save_dict)
        return out

    # -- the CachedOp analogue ----------------------------------------------
    def _call_cached(self, args):
        import jax

        flat_args = [a for a in args if isinstance(a, NDArray)]
        try:
            params = {k: p.data() for k, p in self._collect_all_reg_params().items()}
        except DeferredInitializationError:
            # first call with deferred params: run eagerly once to infer
            out = self.forward(*args)
            params = {k: p.data() for k, p in self._collect_all_reg_params().items()}
            return out
        key = (
            tuple((tuple(a.shape), str(a.dtype)) for a in flat_args),
            autograd.is_training(),
            autograd.is_recording(),
        )
        if self._cached_fn is None or self._cache_key != key:
            self._cached_fn = self._build_cache(args, params)
            self._cache_key = key
        return self._cached_fn(args, params)

    def _collect_all_reg_params(self):
        out = {}

        def walk(block):
            for name, p in block._reg_params.items():
                out[p.name] = p
            for c in block._children.values():
                walk(c)

        walk(self)
        return out

    def _build_cache(self, args, params):
        """Trace self.forward into a jitted function of (inputs, params).

        Training mode with autograd recording uses a custom tape entry so
        backward flows through the single compiled program.
        """
        import jax

        self_ref = self
        is_train = autograd.is_training()
        param_names = list(params.keys())

        def pure_fn(key, input_vals, param_vals):
            from .. import random as _rnd

            # run forward with NDArray views over traced values
            wrapped_inputs = [_wrap_raw(v) for v in input_vals]
            holders = {}
            wrapped = {}
            all_params = self_ref._collect_all_reg_params()
            for name, p in all_params.items():
                holders[name] = p._data
                wrapped[name] = p._data = _wrap_raw(param_vals[name])
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(is_train)
            tok = _rnd.push_trace_key(key)
            try:
                out = self_ref.forward(*wrapped_inputs)
            finally:
                _rnd.pop_trace_key(tok)
                autograd.set_recording(prev_rec)
                autograd.set_training(prev_train)
                for name, p in all_params.items():
                    p._data = holders[name]
            # stateful-op aux mutation (BatchNorm running stats): the post
            # hooks rebound the wrapped views in place; surface the updates
            # as extra outputs so they survive the functional jit boundary
            # (ref: CachedOp executes ops that mutate aux NDArrays directly,
            # cached_op.cc:332 — here state must be threaded out explicitly)
            mutated = {name: w._data() for name, w in wrapped.items()
                       if w._data() is not param_vals[name]}
            if isinstance(out, (list, tuple)):
                return [o._data() for o in out], mutated
            return out._data(), mutated

        jitted = jax.jit(pure_fn)

        def apply_mutated(mutated):
            if not mutated:
                return
            all_params = self_ref._collect_all_reg_params()
            for name, val in mutated.items():
                p = all_params.get(name)
                if p is not None and p._data is not None:
                    p._data._rebind(val)

        def run(call_args, call_params):
            from .. import random as _rnd

            input_vals = [a._data() for a in call_args if isinstance(a, NDArray)]
            param_vals = {k: v._data() for k, v in call_params.items()}
            key = _rnd.next_key(current_context())
            if autograd.is_recording():
                return _recorded_apply(jitted, key, input_vals, param_vals,
                                       [a for a in call_args if isinstance(a, NDArray)],
                                       self_ref._collect_all_reg_params(),
                                       apply_mutated)
            out, mutated = jitted(key, input_vals, param_vals)
            apply_mutated(mutated)
            if isinstance(out, list):
                return [_wrap_raw(o) for o in out]
            return _wrap_raw(out)

        return run


def _recorded_apply(jitted, key, input_vals, param_vals, input_arrays,
                    params_map, apply_mutated=None):
    """Run the cached fn under autograd: record one tape node whose vjp is
    the vjp of the whole compiled program (CachedOp::Backward parity)."""
    param_names = list(param_vals.keys())

    def fn_of_all(inp_list, pv_list):
        pv = dict(zip(param_names, pv_list))
        out, _mutated = jitted(key, inp_list, pv)
        return out

    out, mutated = jitted(key, input_vals, param_vals)
    if apply_mutated is not None:
        apply_mutated(mutated)
    single = not isinstance(out, list)
    outs_list = [out] if single else list(out)

    class _CachedCustom:
        def backward_cotangents(self, node, out_cotangents):
            import jax
            import jax.numpy as jnp

            def f(*flat):
                n_in = len(input_vals)
                inp = list(flat[:n_in])
                pv = list(flat[n_in:])
                res = fn_of_all(inp, pv)
                return tuple(res) if isinstance(res, list) else (res,)

            primals = list(input_vals) + [param_vals[n] for n in param_names]
            outs, vjp_fn = jax.vjp(f, *primals)
            cts = tuple(
                c if c is not None else jnp.zeros_like(o)
                for c, o in zip(
                    list(out_cotangents) + [None] * (len(outs) - len(out_cotangents)), outs
                )
            )
            return list(vjp_fn(cts))

    out_arrays = [_wrap_raw(o) for o in outs_list]
    param_ndarrays = [params_map[n].data() for n in param_names]
    autograd.record_op(
        None, {}, list(input_arrays) + param_ndarrays, out_arrays,
        list(input_vals) + [param_vals[n] for n in param_names],
        custom=_CachedCustom(),
    )
    return out_arrays[0] if single else out_arrays


class SymbolBlock(HybridBlock):
    """Wrap a Symbol + params as a Block (ref: block.py:542)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=None)
        from .. import symbol as sym_mod

        if isinstance(inputs, sym_mod.Symbol):
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(outputs)
        self._cached_graph = (inputs, outputs)
        input_names = {i.name for i in inputs}
        self._input_names = [i.name for i in inputs]
        arg_params = params or {}
        for name in outputs.list_inputs():
            if name not in input_names:
                p = Parameter(name, allow_deferred_init=True)
                if name in arg_params:
                    p.shape = arg_params[name].shape
                    p.initialize()
                    p.set_data(arg_params[name])
                self.params._params[name] = p
        self._out_symbol = outputs
        self._exec_cache = {}

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        from ..ndarray.utils import load as nd_load

        outputs = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        params = {}
        if param_file is not None:
            raw = nd_load(param_file)
            for k, v in raw.items():
                name = k.split(":", 1)[1] if ":" in k else k
                params[name] = v
        return SymbolBlock(outputs, inputs, params=params)

    def forward(self, *args):
        from ..executor import Executor

        values = {}
        for name, a in zip(self._input_names, args):
            values[name] = a
        arg_arrays = {}
        for name in self._out_symbol.list_inputs():
            if name in values:
                arg_arrays[name] = values[name]
            else:
                arg_arrays[name] = self.params[name].data()
        aux_names = set(self._out_symbol.list_auxiliary_states())
        args_d = {k: v for k, v in arg_arrays.items() if k not in aux_names}
        aux_d = {k: v for k, v in arg_arrays.items() if k in aux_names}
        # cache the Executor per input signature so jit compilation is paid
        # once, not per call (CachedOp parity)
        cache_key = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        ex = self._exec_cache.get(cache_key)
        if ex is None:
            ex = Executor(self._out_symbol, args[0].ctx, args_d, None, "null", aux_d)
            self._exec_cache[cache_key] = ex
        else:
            for k, v in args_d.items():
                ex.arg_dict[k]._rebind(v._data())
            for k, v in aux_d.items():
                ex.aux_dict[k]._rebind(v._data())
        outs = ex.forward(is_train=autograd.is_training())
        return outs[0] if len(outs) == 1 else outs
