"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as np

from ....ndarray import ndarray as nd
from ....ndarray.ndarray import NDArray, invoke
from ...block import Block, HybridBlock
from ...nn import HybridSequential, Sequential


class Compose(Sequential):
    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def forward(self, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __init__(self):
        super().__init__()

    def forward(self, x):
        out = x.astype(np.float32) / 255.0
        if out.ndim == 3:
            return out.transpose((2, 0, 1))
        return out.transpose((0, 3, 1, 2))


class Normalize(HybridBlock):
    def __init__(self, mean, std):
        super().__init__()
        self._mean = np.asarray(mean, dtype=np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, dtype=np.float32).reshape(-1, 1, 1)

    def forward(self, x):
        return (x - nd.array(self._mean, ctx=x.ctx)) / nd.array(self._std, ctx=x.ctx)


class Resize(Block):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        import jax

        arr = x._data().astype("float32")
        h, w = self._size[1], self._size[0]
        out = jax.image.resize(arr, (h, w, arr.shape[2]), method="bilinear")
        return NDArray(out.astype(x._data().dtype), ctx=x.ctx)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[0], x.shape[1]
        y0 = max((H - h) // 2, 0)
        x0 = max((W - w) // 2, 0)
        return NDArray(x._data()[y0 : y0 + h, x0 : x0 + w], ctx=x.ctx)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0), interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import jax

        H, W = x.shape[0], x.shape[1]
        area = H * W
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            aspect = np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target_area * aspect)))
            h = int(round(np.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = np.random.randint(0, W - w + 1)
                y0 = np.random.randint(0, H - h + 1)
                crop = x._data()[y0 : y0 + h, x0 : x0 + w].astype("float32")
                out = jax.image.resize(
                    crop, (self._size[1], self._size[0], crop.shape[2]), method="bilinear"
                )
                return NDArray(out.astype(x._data().dtype), ctx=x.ctx)
        return CenterCrop(self._size).forward(x)


class RandomFlipLeftRight(Block):
    def __init__(self):
        super().__init__()

    def forward(self, x):
        if np.random.rand() < 0.5:
            return NDArray(x._data()[:, ::-1], ctx=x.ctx)
        return x


class RandomFlipTopBottom(Block):
    def __init__(self):
        super().__init__()

    def forward(self, x):
        if np.random.rand() < 0.5:
            return NDArray(x._data()[::-1], ctx=x.ctx)
        return x
