"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

Local-file based (MNIST idx files, CIFAR binary batches, image folders);
downloads are disabled in this environment — point `root` at local copies.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ....base import MXNetError
from ....ndarray import ndarray as nd
from ..dataset import Dataset, RecordFileDataset


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx files (ref: datasets.py MNIST)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        self._train_data = ("train-images-idx3-ubyte.gz", "")
        self._train_label = ("train-labels-idx1-ubyte.gz", "")
        self._test_data = ("t10k-images-idx3-ubyte.gz", "")
        self._test_label = ("t10k-labels-idx1-ubyte.gz", "")
        super().__init__(root, transform)

    def _open(self, fname):
        path = os.path.join(self._root, fname)
        if not os.path.exists(path) and path.endswith(".gz"):
            path = path[:-3]
        if not os.path.exists(path):
            raise MXNetError("MNIST file %s not found (downloads disabled; place files in %s)"
                             % (fname, self._root))
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _get_data(self):
        data_file = self._train_data[0] if self._train else self._test_data[0]
        label_file = self._train_label[0] if self._train else self._test_label[0]
        with self._open(label_file) as fin:
            struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.int32)
        with self._open(data_file) as fin:
            struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(), dtype=np.uint8)
            data = data.reshape(len(label), 28, 28, 1)
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root=root, train=train, transform=transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 from local binary batches (ref: datasets.py CIFAR10)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(-1, 3072 + 1)
        return (
            data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1),
            data[:, 0].astype(np.int32),
        )

    def _get_data(self):
        sub = os.path.join(self._root, "cifar-10-batches-bin")
        base = sub if os.path.isdir(sub) else self._root
        if self._train:
            files = [os.path.join(base, "data_batch_%d.bin" % i) for i in range(1, 6)]
        else:
            files = [os.path.join(base, "test_batch.bin")]
        for f in files:
            if not os.path.exists(f):
                raise MXNetError("CIFAR file %s not found (downloads disabled)" % f)
        data, label = zip(*[self._read_batch(f) for f in files])
        data = np.concatenate(data)
        label = np.concatenate(label)
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        super().__init__(root=root, train=train, transform=transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = np.frombuffer(fin.read(), dtype=np.uint8).reshape(-1, 3072 + 2)
        return (
            data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1),
            data[:, 0 + self._fine_label].astype(np.int32),
        )

    def _get_data(self):
        sub = os.path.join(self._root, "cifar-100-binary")
        base = sub if os.path.isdir(sub) else self._root
        name = "train.bin" if self._train else "test.bin"
        f = os.path.join(base, name)
        if not os.path.exists(f):
            raise MXNetError("CIFAR100 file %s not found (downloads disabled)" % f)
        data, label = self._read_batch(f)
        self._data = nd.array(data, dtype=np.uint8)
        self._label = label


class ImageRecordDataset(RecordFileDataset):
    """Images packed in a RecordIO file (ref: datasets.py ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio

        record = super().__getitem__(idx)
        header, img_bytes = recordio.unpack(record)
        from ....image.image import imdecode_bytes

        img = nd.array(imdecode_bytes(img_bytes, self._flag))
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """A dataset of images arranged in class folders (ref: ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".npy"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        fname, label = self.items[idx]
        if fname.endswith(".npy"):
            img = nd.array(np.load(fname))
        else:
            from ....image.image import imread

            img = imread(fname, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
