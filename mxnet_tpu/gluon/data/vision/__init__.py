"""Vision datasets + transforms (ref: python/mxnet/gluon/data/vision/)."""
from . import transforms  # noqa: F401
from .datasets import CIFAR10, CIFAR100, MNIST, FashionMNIST, ImageFolderDataset, ImageRecordDataset  # noqa: F401
