"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference uses multiprocess workers + shared-memory NDArrays
(Context::kCPUShared). Here: thread-pool workers (numpy decode releases the
GIL) feeding a bounded prefetch queue — device_put happens in the consumer,
so host decode overlaps TPU compute.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ...ndarray import ndarray as nd
from ...ndarray.ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler


def default_batchify_fn(data):
    """Stack samples into batch arrays."""
    if isinstance(data[0], NDArray):
        return nd.array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return nd.array(data, dtype=data.dtype if data.dtype != np.float64 else np.float32)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be specified if "
                "batch_sampler is specified."
            )
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch or 2 * max(self._num_workers, 1))

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[idx] for idx in batch])
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        batches = list(self._batch_sampler)
        out_q = [None] * len(batches)
        done = [False] * len(batches)
        lock = threading.Lock()
        next_job = [0]
        sem = threading.Semaphore(self._prefetch)

        def worker():
            while True:
                with lock:
                    if next_job[0] >= len(batches):
                        return
                    job = next_job[0]
                    next_job[0] += 1
                sem.acquire()
                res = self._batchify_fn([self._dataset[idx] for idx in batches[job]])
                with lock:
                    out_q[job] = res
                    done[job] = True

        threads = [threading.Thread(target=worker, daemon=True) for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        for i in range(len(batches)):
            while True:
                with lock:
                    if done[i]:
                        res = out_q[i]
                        out_q[i] = None
                        break
                threading.Event().wait(0.001)
            sem.release()
            yield res
        for t in threads:
            t.join(timeout=0.1)

    def __len__(self):
        return len(self._batch_sampler)
