"""Model-zoo helper blocks (ref:
python/mxnet/gluon/model_zoo/custom_layers.py — HybridConcurrent,
Identity)."""
from __future__ import annotations

from ...ndarray.ndarray import invoke
from ..nn.basic_layers import HybridBlock


class HybridConcurrent(HybridBlock):
    """Run child blocks on the same input and concat their outputs."""

    def __init__(self, concat_dim=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.concat_dim = concat_dim
        self._layers = []

    def add(self, block):
        self._layers.append(block)
        self.register_child(block)

    def forward(self, x):
        outs = [block(x) for block in self._layers]
        if len(outs) == 1:
            return outs[0]
        return invoke("Concat", outs,
                      {"dim": self.concat_dim, "num_args": len(outs)})


class Identity(HybridBlock):
    """Pass-through (useful as a no-op branch of HybridConcurrent)."""

    def forward(self, x):
        return x
