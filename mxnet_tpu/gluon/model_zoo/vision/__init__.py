"""Model zoo (ref: python/mxnet/gluon/model_zoo/vision/__init__.py)."""
from __future__ import annotations

from ....base import MXNetError
from .alexnet import AlexNet, alexnet
from .mobilenet import MobileNet, mobilenet0_25, mobilenet0_5, mobilenet0_75, mobilenet1_0
from .resnet import *  # noqa: F401,F403
from .resnet import get_resnet
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19, vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn
from .densenet import DenseNet, densenet121, densenet161, densenet169, densenet201
from .inception import Inception3, inception_v3

_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1, "resnet50_v1": resnet50_v1,
    "resnet101_v1": resnet101_v1, "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2, "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2, "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn, "vgg19_bn": vgg19_bn,
    "alexnet": alexnet,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "inceptionv3": inception_v3,
}


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            "Model %s is not supported. Available options are:\n\t%s"
            % (name, "\n\t".join(sorted(_models.keys())))
        )
    return _models[name](**kwargs)
