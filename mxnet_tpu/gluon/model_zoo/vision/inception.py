"""Inception-V3 (ref: python/mxnet/gluon/model_zoo/vision/inception.py;
architecture per Szegedy et al., "Rethinking the Inception Architecture").

Built from HybridSequential/HybridConcurrent so the whole network traces
to one XLA program under hybridize; the stacked 1x7/7x1 factorized convs
map straight onto the MXU.
"""
from __future__ import annotations

from ...nn import (Activation, AvgPool2D, BatchNorm, Conv2D, Dense, Dropout,
                   Flatten, GlobalAvgPool2D, HybridSequential, MaxPool2D)
from ...nn.basic_layers import HybridBlock
from ..custom_layers import HybridConcurrent

__all__ = ["Inception3", "inception_v3"]


def _conv(channels, kernel_size, strides=1, padding=0):
    out = HybridSequential()
    out.add(Conv2D(channels, kernel_size, strides=strides, padding=padding,
                   use_bias=False),
            BatchNorm(epsilon=0.001),
            Activation("relu"))
    return out


def _branch(use_pool, *conv_settings):
    out = HybridSequential()
    if use_pool == "avg":
        out.add(AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(MaxPool2D(pool_size=3, strides=2))
    for channels, kernel, stride, pad in conv_settings:
        out.add(_conv(channels, kernel, stride, pad))
    return out


def _make_A(pool_features):
    out = HybridConcurrent(concat_dim=1)
    out.add(_branch(None, (64, 1, 1, 0)))
    out.add(_branch(None, (48, 1, 1, 0), (64, 5, 1, 2)))
    out.add(_branch(None, (64, 1, 1, 0), (96, 3, 1, 1), (96, 3, 1, 1)))
    out.add(_branch("avg", (pool_features, 1, 1, 0)))
    return out


def _make_B():
    out = HybridConcurrent(concat_dim=1)
    out.add(_branch(None, (384, 3, 2, 0)))
    out.add(_branch(None, (64, 1, 1, 0), (96, 3, 1, 1), (96, 3, 2, 0)))
    out.add(_branch("max"))
    return out


def _make_C(channels_7x7):
    c7 = channels_7x7
    out = HybridConcurrent(concat_dim=1)
    out.add(_branch(None, (192, 1, 1, 0)))
    out.add(_branch(None, (c7, 1, 1, 0), (c7, (1, 7), 1, (0, 3)),
                    (192, (7, 1), 1, (3, 0))))
    out.add(_branch(None, (c7, 1, 1, 0), (c7, (7, 1), 1, (3, 0)),
                    (c7, (1, 7), 1, (0, 3)), (c7, (7, 1), 1, (3, 0)),
                    (192, (1, 7), 1, (0, 3))))
    out.add(_branch("avg", (192, 1, 1, 0)))
    return out


def _make_D():
    out = HybridConcurrent(concat_dim=1)
    out.add(_branch(None, (192, 1, 1, 0), (320, 3, 2, 0)))
    out.add(_branch(None, (192, 1, 1, 0), (192, (1, 7), 1, (0, 3)),
                    (192, (7, 1), 1, (3, 0)), (192, 3, 2, 0)))
    out.add(_branch("max"))
    return out


class _SplitConv(HybridBlock):
    """A conv trunk whose output forks into parallel 1x3 / 3x1 convs
    (the expanded-filter-bank E block)."""

    def __init__(self, trunk_settings, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        trunk = HybridSequential()
        for channels, kernel, stride, pad in trunk_settings:
            trunk.add(_conv(channels, kernel, stride, pad))
        self.trunk = trunk
        self.register_child(trunk)
        fork = HybridConcurrent(concat_dim=1)
        fork.add(_conv(384, (1, 3), 1, (0, 1)))
        fork.add(_conv(384, (3, 1), 1, (1, 0)))
        self.fork = fork
        self.register_child(fork)

    def forward(self, x):
        return self.fork(self.trunk(x))


def _make_E():
    out = HybridConcurrent(concat_dim=1)
    out.add(_branch(None, (320, 1, 1, 0)))
    out.add(_SplitConv([(384, 1, 1, 0)]))
    out.add(_SplitConv([(448, 1, 1, 0), (384, 3, 1, 1)]))
    out.add(_branch("avg", (192, 1, 1, 0)))
    return out


class Inception3(HybridBlock):
    """Inception v3: 299x299 input
    (ref: inception.py:155 Inception3)."""

    def __init__(self, classes=1000, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            features = HybridSequential()
            features.add(_conv(32, 3, 2, 0),
                         _conv(32, 3, 1, 0),
                         _conv(64, 3, 1, 1),
                         MaxPool2D(pool_size=3, strides=2),
                         _conv(80, 1, 1, 0),
                         _conv(192, 3, 1, 0),
                         MaxPool2D(pool_size=3, strides=2),
                         _make_A(32), _make_A(64), _make_A(64),
                         _make_B(),
                         _make_C(128), _make_C(160), _make_C(160),
                         _make_C(192),
                         _make_D(),
                         _make_E(), _make_E())
            self.features = features
            self.register_child(features)
            classifier = HybridSequential()
            classifier.add(GlobalAvgPool2D(),
                           Dropout(0.5),
                           Flatten(),
                           Dense(classes))
            self.classifier = classifier
            self.register_child(classifier)

    def forward(self, x):
        return self.classifier(self.features(x))


def inception_v3(pretrained=False, ctx=None, **kwargs):
    """Inception-V3 constructor (ref: inception.py inception_v3)."""
    net = Inception3(**kwargs)
    if pretrained:
        from ..model_store import get_model_file
        from ...ndarray import utils as nd_utils  # noqa: F401

        net.load_params(get_model_file("inceptionv3"), ctx=ctx)
    return net
