"""Pretrained model file store (ref:
python/mxnet/gluon/model_zoo/model_store.py — get_model_file/purge with
a sha1-named local cache under ~/.mxnet/models).

Zero-egress design: the cache and integrity-check logic is full parity;
fetching honors ``MXNET_GLUON_REPO`` when it points at a local directory
or ``file://`` tree (the common air-gapped TPU-pod setup), and raises a
clear error instead of attempting network I/O otherwise.
"""
from __future__ import annotations

import hashlib
import os
import shutil
import zipfile

from ...base import MXNetError

# name -> sha1 of the released .params — DATA parity with the reference
# table (model_store.py:31): these identify the official artifacts, so
# the values must be the published checksums verbatim.
_model_sha1 = {name: checksum for checksum, name in [
    ("44335d1f0046b328243b32a26a4fbd62d9057b45", "alexnet"),
    ("f27dbf2dbd5ce9a80b102d89c7483342cd33cb31", "densenet121"),
    ("b6c8a95717e3e761bd88d145f4d0a214aaa515dc", "densenet161"),
    ("2603f878403c6aa5a71a124c4a3307143d6820e9", "densenet169"),
    ("1cdbc116bc3a1b65832b18cf53e1cb8e7da017eb", "densenet201"),
    ("ed47ec45a937b656fcc94dabde85495bbef5ba1f", "inceptionv3"),
    ("d2b128fa89477c2e20061607a53a8d9f66ce239d", "resnet101_v1"),
    ("6562166cd597a6328a32a0ce47bb651df80b3bbb", "resnet152_v1"),
    ("38d6d423c22828718ec3397924b8e116a03e6ac0", "resnet18_v1"),
    ("4dc2c2390a7c7990e0ca1e53aeebb1d1a08592d1", "resnet34_v1"),
    ("2a903ab21260c85673a78fe65037819a843a1f43", "resnet50_v1"),
    ("8aacf80ff4014c1efa2362a963ac5ec82cf92d5b", "resnet18_v2"),
    ("0ed3cd06da41932c03dea1de7bc2506ef3fb97b3", "resnet34_v2"),
    ("eb7a368774aa34a12ed155126b641ae7556dad9d", "resnet50_v2"),
    ("264ba4970a0cc87a4f15c96e25246a1307caf523", "squeezenet1.0"),
    ("33ba0f93753c83d86e1eb397f38a667eaf2e9376", "squeezenet1.1"),
    ("dd221b160977f36a53f464cb54648d227c707a05", "vgg11"),
    ("ee79a8098a91fbe05b7a973fed2017a6117723a8", "vgg11_bn"),
    ("6bc5de58a05a5e2e7f493e2d75a580d83efde38c", "vgg13"),
    ("7d97a06c3c7a1aecc88b6e7385c2b373a249e95e", "vgg13_bn"),
    ("649467530119c0f78c4859999e264e7bf14471a9", "vgg16"),
    ("6b9dbe6194e5bfed30fd7a7c9a71f7e5a276cb14", "vgg16_bn"),
    ("f713436691eee9a20d70a145ce0d53ed24bf7399", "vgg19"),
    ("9730961c9cea43fd7eeefb00d792e386c45847d6", "vgg19_bn"),
]}

apache_repo_url = "https://apache-mxnet.s3-accelerate.dualstack.amazonaws.com/"
_url_format = "{repo_url}gluon/models/{file_name}.zip"


def short_hash(name):
    if name not in _model_sha1:
        raise ValueError("Pretrained model for %s is not available." % name)
    return _model_sha1[name][:8]


def check_sha1(filename, sha1_hash):
    """True when the file's sha1 matches (ref model_store.py check)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1 << 20)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def get_model_file(name, root="~/.mxnet/models/"):
    r"""Return the path of a pretrained .params file, fetching into the
    cache when a local repo is configured.

    File name: ``{name}-{short_hash}.params`` under ``root`` —
    byte-parity with the reference cache layout, so a cache populated by
    the original framework is picked up as-is."""
    file_name = "{name}-{short_hash}".format(name=name,
                                             short_hash=short_hash(name))
    root = os.path.expanduser(root)
    file_path = os.path.join(root, file_name + ".params")
    sha1_hash = _model_sha1[name]
    if os.path.exists(file_path):
        if check_sha1(file_path, sha1_hash):
            return file_path
        print("Mismatch in the content of model file detected. Downloading again.")
    else:
        print("Model file is not found. Downloading.")

    os.makedirs(root, exist_ok=True)

    repo_url = os.environ.get("MXNET_GLUON_REPO", apache_repo_url)
    zip_file_path = os.path.join(root, file_name + ".zip")
    if repo_url.startswith("file://"):
        repo_url = repo_url[len("file://"):]
    if os.path.isdir(repo_url):
        # air-gapped repo: a directory holding {file_name}.zip or .params
        src_params = os.path.join(repo_url, file_name + ".params")
        src_zip = os.path.join(repo_url, file_name + ".zip")
        if os.path.exists(src_params):
            shutil.copyfile(src_params, file_path)
        elif os.path.exists(src_zip):
            shutil.copyfile(src_zip, zip_file_path)
            with zipfile.ZipFile(zip_file_path) as zf:
                zf.extractall(root)
            os.remove(zip_file_path)
        else:
            raise MXNetError(
                "pretrained %r not found in local repo %s" % (name, repo_url))
    else:
        raise MXNetError(
            "no network egress in this environment: place %s.params under "
            "%s (the reference cache layout), or set MXNET_GLUON_REPO to a "
            "local directory / file:// tree holding the released artifacts"
            % (file_name, root))

    if check_sha1(file_path, sha1_hash):
        return file_path
    raise MXNetError("Downloaded file has different hash. Please try again.")


def purge(root="~/.mxnet/models/"):
    """Remove every cached .params (ref model_store.py:111)."""
    root = os.path.expanduser(root)
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
