"""Gluon loss blocks.

Reference counterpart: ``python/mxnet/gluon/loss.py`` — L2/L1/SigmoidBCE/
SoftmaxCE/KL/Huber/Hinge/SquaredHinge/Logistic/Triplet/CTC losses.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import ndarray as nd
from ..ndarray.ndarray import NDArray, invoke
from .block import HybridBlock


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        assert isinstance(weight, (float, int)), "weight must be a number"
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    if pred.shape != label.shape:
        label = label.reshape(pred.shape)
    return label


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        s = "{name}(batch_axis={_batch_axis}, w={_weight})"
        return s.format(name=self.__class__.__name__, **self.__dict__)


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = (pred - label).square()
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = (pred - label).abs()
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            # max(x,0) - x*z + log(1+exp(-|x|)) — numerically stable
            loss = invoke("relu", [pred], {}) - pred * label + (
                invoke("Activation", [(-pred.abs())], {"act_type": "softrelu"})
            )
        else:
            eps = 1e-12
            loss = -((pred + eps).log() * label + (1.0 - pred + eps).log() * (1.0 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    def __init__(self, axis=-1, sparse_label=True, from_logits=False, weight=None,
                 batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = pred.log_softmax(axis=self._axis)
        if self._sparse_label:
            loss = -invoke("pick", [pred, label], {"axis": self._axis, "keepdims": True})
        else:
            label = _reshape_like(pred, label)
            loss = -(pred * label).sum(axis=self._axis, keepdims=True)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = pred.log_softmax(axis=self._axis)
        loss = label * ((label + 1e-12).log() - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = (pred - label).abs()
        small = loss < self._rho
        loss = small * (loss.square() / (2 * self._rho)) + (1 - small) * (loss - self._rho / 2)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = invoke("relu", [self._margin - pred * label], {})
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = invoke("relu", [self._margin - pred * label], {}).square()
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format
        if self._label_format not in ["signed", "binary"]:
            raise ValueError("label_format can only be signed or binary, received %s" % label_format)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = invoke("relu", [pred], {}) - pred * label + (
            invoke("Activation", [(-pred.abs())], {"act_type": "softrelu"})
        )
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean(axis=tuple(range(1, loss.ndim))) if loss.ndim > 1 else loss


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(pred, positive)
        negative = _reshape_like(pred, negative)
        loss = (
            (pred - positive).square() - (pred - negative).square()
        ).sum(axis=tuple(range(1, pred.ndim))) + self._margin
        loss = invoke("relu", [loss], {})
        return _apply_weighting(loss, self._weight, sample_weight)


class CTCLoss(Loss):
    """CTC loss (ref: gluon/loss.py CTCLoss over warp-ctc; here a pure-XLA
    dynamic-program implementation in ops/contrib.py)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        assert layout in ["NTC", "TNC"]
        assert label_layout in ["NT", "TN"]
        self._layout = layout
        self._label_layout = label_layout
        super().__init__(weight, label_layout.find("N"), **kwargs)

    def forward(self, pred, label, pred_lengths=None, label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)
        if self._batch_axis == 1:
            label = label.swapaxes(0, 1)
        loss = invoke(
            "_contrib_ctc_loss",
            [pred, label, pred_lengths, label_lengths],
            {
                "use_data_lengths": pred_lengths is not None,
                "use_label_lengths": label_lengths is not None,
                "blank_label": "last",
            },
        )
        return _apply_weighting(loss, self._weight, sample_weight)
