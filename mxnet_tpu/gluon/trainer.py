"""Gluon Trainer.

Reference counterpart: ``python/mxnet/gluon/trainer.py:59-201`` (auto
kvstore via _create_kvstore, step() = push/pull or local update,
update_on_kvstore for dist). Single-buffer parameters mean step() reduces
to one fused optimizer-op call per parameter.
"""
from __future__ import annotations

from .. import optimizer as opt
from ..base import MXNetError
from ..kvstore import KVStore
from ..model import _create_kvstore
from .parameter import Parameter, ParameterDict


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError("First argument must be a list or dict of Parameters, "
                             "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError("First argument must be a list or dict of Parameters, "
                                 "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore_spec = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._param_idx = {p.name: i for i, p in enumerate(self._params)}

    def _init_optimizer(self, optimizer, optimizer_params):
        # key by BOTH index (local updater path calls with int index) and
        # name (kvstore updater path calls with string key) so per-parameter
        # lr_mult/wd_mult resolve either way
        param_dict = {i: param for i, param in enumerate(self._params)}
        param_dict.update({param.name: param for param in self._params})
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, (
                "optimizer_params must be None if optimizer is an Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict, **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        arg_arrays = {param.name: param.data() for param in self._params}
        kvstore, update_on_kvstore = _create_kvstore(self._kvstore_spec, 1, arg_arrays)
        if self._update_on_kvstore is not None:
            update_on_kvstore = self._update_on_kvstore
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                kvstore.init(param.name, param.data())
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore if kvstore else False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step given accumulated grads."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _allreduce_grads(self):
        # push all keys before the first pull so a dist kvstore can batch
        # every gradient into one flattened collective (kvstore._flush)
        if self._kvstore and not self._update_on_kvstore:
            live = []
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.push(param.name, param.list_grad(), priority=-i)
                    live.append((i, param))
            for i, param in live:
                self._kvstore.pull(param.name, param.list_grad(), priority=-i)

    def _update(self, ignore_stale_grad=False):
        if self._kvstore and self._update_on_kvstore:
            live = []
            for i, param in enumerate(self._params):
                if param.grad_req == "null":
                    continue
                # async server tier: push returns a future immediately;
                # the batched pull below waits only on these keys
                self._kvstore.push(param.name, param.list_grad(), priority=-i)
                live.append(param)
            if live:
                # one batched pull (per-shard multi-key frames on the
                # server tier) instead of a round trip per parameter
                self._kvstore.pull([p.name for p in live],
                                   [p.data() for p in live], priority=0)
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(), param.list_grad()):
                upd(i, grad, arr)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._optimizer
