"""Gluon convolution & pooling layers.

Reference counterpart: ``python/mxnet/gluon/nn/conv_layers.py`` (Conv1D/2D/3D,
Conv2DTranspose, Max/Avg/GlobalPool). All lower to the Convolution/Pooling
ops → lax.conv_general_dilated/reduce_window on the MXU.
"""
from __future__ import annotations

import numpy as np

from ...ndarray.ndarray import invoke
from ..parameter import DeferredInitializationError
from .basic_layers import Activation, _ParamLayer, HybridBlock


class _Conv(_ParamLayer):
    def __init__(self, channels, kernel_size, strides, padding, dilation, groups,
                 layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._channels = channels
            self._in_channels = in_channels
            if isinstance(kernel_size, int):
                kernel_size = (kernel_size,)
            if isinstance(strides, int):
                strides = (strides,) * len(kernel_size)
            if isinstance(padding, int):
                padding = (padding,) * len(kernel_size)
            if isinstance(dilation, int):
                dilation = (dilation,) * len(kernel_size)
            self._op_name = op_name
            self._kwargs = {
                "kernel": kernel_size, "stride": strides, "dilate": dilation,
                "pad": padding, "num_filter": channels, "num_group": groups,
                "no_bias": not use_bias,
            }
            if adj is not None:
                self._kwargs["adj"] = adj
            self._kernel_size = kernel_size
            self._groups = groups
            self._use_bias = use_bias

            if op_name == "Convolution":
                wshape = (channels, in_channels // groups if in_channels else 0) + tuple(kernel_size)
            else:  # Deconvolution: (in, out/groups, *k)
                wshape = (in_channels if in_channels else 0, channels // groups) + tuple(kernel_size)
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer, allow_deferred_init=True
            )
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer, allow_deferred_init=True
                )
            self.act = Activation(activation, prefix=activation + "_") if activation else None

    def _infer_param_shapes(self, x):
        c_in = x.shape[1]
        if self._op_name == "Convolution":
            self.weight.shape = (self._channels, c_in // self._groups) + tuple(self._kernel_size)
        else:
            self.weight.shape = (c_in, self._channels // self._groups) + tuple(self._kernel_size)

    def forward(self, x):
        params = self._get_params(x)
        out = invoke(self._op_name, [x, params["weight"], params.get("bias")], self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        if isinstance(output_padding, int):
            output_padding = (output_padding,) * 2
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 2
        super().__init__(channels, kernel_size, strides, padding, dilation, groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, global_pool, pool_type, ceil_mode=False, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        if isinstance(strides, int):
            strides = (strides,) * len(pool_size)
        if isinstance(padding, int):
            padding = (padding,) * len(pool_size)
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid",
        }

    def _alias(self):
        return "pool"

    def forward(self, x):
        return invoke("Pooling", [x], self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW", ceil_mode=False, **kwargs):
        super().__init__((pool_size,) if isinstance(pool_size, int) else pool_size,
                         strides, padding, False, "max", ceil_mode, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW", ceil_mode=False, **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 2
        super().__init__(pool_size, strides, padding, False, "max", ceil_mode, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW", ceil_mode=False, **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 3
        super().__init__(pool_size, strides, padding, False, "max", ceil_mode, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW", ceil_mode=False, **kwargs):
        super().__init__((pool_size,) if isinstance(pool_size, int) else pool_size,
                         strides, padding, False, "avg", ceil_mode, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW", ceil_mode=False, **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 2
        super().__init__(pool_size, strides, padding, False, "avg", ceil_mode, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW", ceil_mode=False, **kwargs):
        if isinstance(pool_size, int):
            pool_size = (pool_size,) * 3
        super().__init__(pool_size, strides, padding, False, "avg", ceil_mode, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, "max", **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, "max", **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "max", **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, "avg", **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, "avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, "avg", **kwargs)
