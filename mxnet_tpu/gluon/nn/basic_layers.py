"""Gluon basic NN layers.

Reference counterpart: ``python/mxnet/gluon/nn/basic_layers.py`` (Sequential,
Dense, Dropout, BatchNorm, Activation, LeakyReLU, Embedding, Flatten,
LayerNorm, InstanceNorm, HybridLambda/Lambda). Layers call the registered
ops, so eager use hits XLA per-op and hybridized use fuses into one program.
"""
from __future__ import annotations

import numpy as np

from ... import autograd
from ...base import MXNetError
from ...ndarray import ndarray as nd_mod
from ...ndarray.ndarray import NDArray, invoke
from ..block import Block, HybridBlock
from ..parameter import DeferredInitializationError


class Sequential(Block):
    """Stack of blocks (ref: basic_layers.py Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                for l in layers:
                    net.add(l)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block.forward(x) if isinstance(block, HybridBlock) and not block._active else block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                for l in layers:
                    net.add(l)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class _ParamLayer(HybridBlock):
    """Common deferred-shape machinery: subclasses define _infer_param_shapes."""

    def _get_params(self, x):
        from ...symbol.symbol import Symbol, var

        if isinstance(x, Symbol):
            # symbolic tracing (export): ONE placeholder per parameter —
            # cached on the Parameter so shared/tied layers reuse the
            # same graph node instead of emitting duplicate arg names
            out = {}
            for k, p in self._reg_params.items():
                ph = getattr(p, "_sym_placeholder", None)
                if ph is None:
                    ph = var(p.name)
                    p._sym_placeholder = ph
                out[k] = ph
            return out
        try:
            return {k: p.data() for k, p in self._reg_params.items()}
        except (DeferredInitializationError, MXNetError):
            self._infer_param_shapes(x)
            for p in self._reg_params.values():
                if p._data is None:
                    p._finish_deferred_init()
            return {k: p.data() for k, p in self._reg_params.items()}

    def _infer_param_shapes(self, x):
        pass


class Dense(_ParamLayer):
    """Fully connected (ref: basic_layers.py Dense)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype=np.float32, weight_initializer=None, bias_initializer="zeros",
                 in_units=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self._flatten = flatten
            self._use_bias = use_bias
            self.weight = self.params.get(
                "weight", shape=(units, in_units), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True,
            )
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer, dtype=dtype,
                    allow_deferred_init=True,
                )
            self.act = Activation(activation, prefix=activation + "_") if activation else None

    def _infer_param_shapes(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def forward(self, x):
        params = self._get_params(x)
        out = invoke(
            "FullyConnected",
            [x, params["weight"], params.get("bias")],
            {"num_hidden": self._units, "no_bias": not self._use_bias, "flatten": self._flatten},
        )
        if self.act is not None:
            out = self.act(out)
        return out


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type

    def forward(self, x):
        return invoke("Activation", [x], {"act_type": self._act_type})


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def forward(self, x):
        return invoke("LeakyReLU", [x, None], {"act_type": "leaky", "slope": self._alpha})


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        return invoke("Dropout", [x], {"p": self._rate, "axes": self._axes})


class BatchNorm(_ParamLayer):
    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros", running_variance_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._kwargs = {
                "axis": axis, "eps": epsilon, "momentum": momentum,
                "fix_gamma": not scale, "use_global_stats": use_global_stats,
            }
            self._axis = axis
            self._in_channels = in_channels
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null", shape=(in_channels,),
                init=gamma_initializer, allow_deferred_init=True, differentiable=scale,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null", shape=(in_channels,),
                init=beta_initializer, allow_deferred_init=True, differentiable=center,
            )
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True, differentiable=False,
            )
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True, differentiable=False,
            )

    def _infer_param_shapes(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if np.dtype(dtype).name == "float16":
            dtype = "float32"
        super().cast(dtype)

    def forward(self, x):
        params = self._get_params(x)
        return invoke(
            "BatchNorm",
            [x, params["gamma"], params["beta"], params["running_mean"], params["running_var"]],
            self._kwargs,
        )


class Embedding(_ParamLayer):
    def __init__(self, input_dim, output_dim, dtype=np.float32,
                 weight_initializer=None, sparse_grad=False, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._input_dim = input_dim
            self._output_dim = output_dim
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), init=weight_initializer,
                dtype=dtype, allow_deferred_init=True,
            )

    def forward(self, x):
        params = self._get_params(x)
        return invoke(
            "Embedding", [x, params["weight"]],
            {"input_dim": self._input_dim, "output_dim": self._output_dim},
        )


class Flatten(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, x):
        return invoke("Flatten", [x], {})


class LayerNorm(_ParamLayer):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._axis = axis
            self._epsilon = epsilon
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null", shape=(in_channels,),
                init=gamma_initializer, allow_deferred_init=True,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null", shape=(in_channels,),
                init=beta_initializer, allow_deferred_init=True,
            )

    def _infer_param_shapes(self, x):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def forward(self, x):
        params = self._get_params(x)
        return invoke(
            "LayerNorm", [x, params["gamma"], params["beta"]],
            {"axis": self._axis, "eps": self._epsilon},
        )


class InstanceNorm(_ParamLayer):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._axis = axis
            self._epsilon = epsilon
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null", shape=(in_channels,),
                init=gamma_initializer, allow_deferred_init=True,
            )
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null", shape=(in_channels,),
                init=beta_initializer, allow_deferred_init=True,
            )

    def _infer_param_shapes(self, x):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def forward(self, x):
        params = self._get_params(x)
        return invoke(
            "InstanceNorm", [x, params["gamma"], params["beta"]], {"eps": self._epsilon}
        )


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd_mod, function), "Function name %s is not found in nd." % function
            self._func_impl = getattr(nd_mod, function)
        elif callable(function):
            self._func_impl = function
        else:
            raise ValueError("Unrecognized function in lambda: {}".format(function))
        self._func_name = getattr(self._func_impl, "__name__", "custom")

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            assert hasattr(nd_mod, function), "Function name %s is not found in nd." % function
            self._func_impl = getattr(nd_mod, function)
        elif callable(function):
            self._func_impl = function
        else:
            raise ValueError("Unrecognized function in lambda: {}".format(function))

    def forward(self, *args):
        return self._func_impl(*args)
