"""Gluon RNN cells: step-wise recurrent units + unroll.

Reference counterpart: ``python/mxnet/gluon/rnn/rnn_cell.py`` (RecurrentCell
ABC, RNNCell/LSTMCell/GRUCell, Sequential/Dropout/Zoneout/Residual/
Bidirectional cells, unroll). On TPU, ``unroll`` over a fixed length traces
to one XLA program; for long sequences prefer the fused RNN layer (scan).
"""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray import ndarray as nd
from ...ndarray.ndarray import NDArray, invoke
from ..block import HybridBlock
from ..parameter import DeferredInitializationError


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


class RecurrentCell(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, ctx=None, dtype=None, **kwargs):
        assert not self._modified, (
            "After applying modifier cells the base cell cannot be called directly. "
            "Call the modifier cell instead."
        )
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"]
            if func is None:
                extra = {}
                if ctx is not None:
                    extra["ctx"] = ctx
                if dtype is not None:
                    extra["dtype"] = dtype
                state = nd.zeros(shape, **extra)
            else:
                state = func(name="%sbegin_state_%d" % (self._prefix, self._init_counter),
                             shape=shape, **kwargs)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None,
               valid_length=None):
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size, ctx=inputs.ctx, dtype=inputs.dtype)
        states = begin_state
        outputs = []
        all_states = []
        seq = [
            invoke("squeeze", [invoke("slice_axis", [inputs], {"axis": axis, "begin": i, "end": i + 1})], {"axis": axis})
            for i in range(length)
        ]
        for i in range(length):
            output, states = self(seq[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [
                invoke("SequenceLast", [invoke("stack", [s[j] for s in all_states], {"axis": 0}), valid_length],
                       {"use_sequence_length": True, "axis": 0})
                for j in range(len(states))
            ]
            outputs = _mask_outputs(outputs, valid_length, axis)
        if merge_outputs is None or merge_outputs:
            outputs = invoke("stack", outputs, {"axis": axis})
        return outputs, states

    def _get_params(self):
        try:
            return {k: p.data() for k, p in self._reg_params.items()}
        except (DeferredInitializationError, MXNetError):
            return None

    def forward(self, inputs, states):
        self._counter += 1
        return self.step(inputs, states)

    def step(self, inputs, states):
        raise NotImplementedError


def _mask_outputs(outputs, valid_length, axis):
    stacked = invoke("stack", outputs, {"axis": 0})
    masked = invoke("SequenceMask", [stacked, valid_length], {"use_sequence_length": True, "axis": 0})
    return [
        invoke("squeeze", [invoke("slice_axis", [masked], {"axis": 0, "begin": i, "end": i + 1})], {"axis": 0})
        for i in range(len(outputs))
    ]


class _BaseFusibleCell(RecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, input_size, ngates,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self._ngates = ngates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ngates * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(ngates * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ngates * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ngates * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def _ensure_params(self, inputs):
        p = self._get_params()
        if p is None:
            self.i2h_weight.shape = (self._ngates * self._hidden_size, inputs.shape[-1])
            for param in self._reg_params.values():
                if param._data is None:
                    param._finish_deferred_init()
            p = {k: v.data() for k, v in self._reg_params.items()}
        return p

    def _fc(self, x, w, b, num_hidden):
        return invoke("FullyConnected", [x, w, b], {"num_hidden": num_hidden, "flatten": False})


class RNNCell(_BaseFusibleCell):
    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(hidden_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, input_size, 1,
                         prefix=prefix, params=params)
        self._activation = activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def step(self, inputs, states):
        p = self._ensure_params(inputs)
        i2h = self._fc(inputs, p["i2h_weight"], p["i2h_bias"], self._hidden_size)
        h2h = self._fc(states[0], p["h2h_weight"], p["h2h_bias"], self._hidden_size)
        output = invoke("Activation", [i2h + h2h], {"act_type": self._activation})
        return output, [output]


class LSTMCell(_BaseFusibleCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(hidden_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, input_size, 4,
                         prefix=prefix, params=params)

    def state_info(self, batch_size=0):
        return [
            {"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
            {"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
        ]

    def _alias(self):
        return "lstm"

    def step(self, inputs, states):
        p = self._ensure_params(inputs)
        H = self._hidden_size
        i2h = self._fc(inputs, p["i2h_weight"], p["i2h_bias"], 4 * H)
        h2h = self._fc(states[0], p["h2h_weight"], p["h2h_bias"], 4 * H)
        gates = i2h + h2h
        slices = invoke("SliceChannel", [gates], {"num_outputs": 4, "axis": 1})
        in_gate = slices[0].sigmoid()
        forget_gate = slices[1].sigmoid()
        in_transform = slices[2].tanh()
        out_gate = slices[3].sigmoid()
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * next_c.tanh()
        return next_h, [next_h, next_c]


class GRUCell(_BaseFusibleCell):
    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None, params=None):
        super().__init__(hidden_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, input_size, 3,
                         prefix=prefix, params=params)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def step(self, inputs, states):
        p = self._ensure_params(inputs)
        H = self._hidden_size
        i2h = self._fc(inputs, p["i2h_weight"], p["i2h_bias"], 3 * H)
        h2h = self._fc(states[0], p["h2h_weight"], p["h2h_bias"], 3 * H)
        i2h_r, i2h_z, i2h_n = invoke("SliceChannel", [i2h], {"num_outputs": 3, "axis": 1})
        h2h_r, h2h_z, h2h_n = invoke("SliceChannel", [h2h], {"num_outputs": 3, "axis": 1})
        reset_gate = (i2h_r + h2h_r).sigmoid()
        update_gate = (i2h_z + h2h_z).sigmoid()
        next_h_tmp = (i2h_n + reset_gate * h2h_n).tanh()
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * states[0]
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def step(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p : p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def step(self, inputs, states):
        if self._rate > 0:
            inputs = invoke("Dropout", [inputs], {"p": self._rate, "axes": self._axes})
        return inputs, states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        assert not base_cell._modified, (
            "Cell %s is already modified. One cell cannot be modified twice" % base_cell.name
        )
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(), params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), (
            "BidirectionalCell doesn't support zoneout. Apply zoneout to the cells underneath instead."
        )
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def step(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        p_outputs, p_states = self.zoneout_outputs, self.zoneout_states

        def mask(p, like):
            return invoke("Dropout", [nd.ones(like.shape, ctx=like.ctx)], {"p": p, "mode": "always"})

        prev_output = self._prev_output if self._prev_output is not None else nd.zeros(next_output.shape, ctx=next_output.ctx)
        output = (
            invoke("where", [mask(p_outputs, next_output), next_output, prev_output], {})
            if p_outputs != 0.0
            else next_output
        )
        new_states = (
            [invoke("where", [mask(p_states, new_s), new_s, old_s], {})
             for new_s, old_s in zip(next_states, states)]
            if p_states != 0.0
            else next_states
        )
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return "residual"

    def step(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(RecurrentCell):
    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC", merge_outputs=None,
               valid_length=None):
        self.reset()
        axis = layout.find("T")
        batch_size = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch_size, ctx=inputs.ctx, dtype=inputs.dtype)
        states = begin_state
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info(batch_size))
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=True, valid_length=valid_length,
        )
        rev_inputs = invoke("SequenceReverse", [inputs.swapaxes(0, axis) if axis != 0 else inputs, valid_length],
                            {"use_sequence_length": valid_length is not None})
        if axis != 0:
            rev_inputs = rev_inputs.swapaxes(0, axis)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=rev_inputs, begin_state=states[n_l:], layout=layout,
            merge_outputs=True, valid_length=valid_length,
        )
        r_outputs_t = r_outputs.swapaxes(0, axis) if axis != 0 else r_outputs
        r_outputs_rev = invoke("SequenceReverse", [r_outputs_t, valid_length],
                               {"use_sequence_length": valid_length is not None})
        if axis != 0:
            r_outputs_rev = r_outputs_rev.swapaxes(0, axis)
        outputs = invoke("Concat", [l_outputs, r_outputs_rev], {"dim": 2})
        return outputs, l_states + r_states
