"""Gluon fused RNN layers: RNN / LSTM / GRU.

Reference counterpart: ``python/mxnet/gluon/rnn/rnn_layer.py:31`` wrapping
the fused ``RNN`` op (cuDNN on GPU; here one lax.scan XLA program, see
ops/nn.py rnn()).
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ...ndarray import ndarray as nd
from ...ndarray.ndarray import NDArray, invoke
from ..block import HybridBlock
from ..parameter import DeferredInitializationError


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        # unfused per-layer/direction params, reference naming
        # (rnn_layer.py:80: l0_i2h_weight, r0_i2h_weight, …) — fused into
        # the flat cuDNN-style vector only at the RNN op boundary
        ng, H = self._gates, hidden_size
        self._rnn_param_names = []
        with self.name_scope():
            for layer in range(num_layers):
                for d in ("l", "r")[: self._dir]:
                    in_size = input_size if layer == 0 else H * self._dir
                    names = ["%s%d_i2h_weight" % (d, layer),
                             "%s%d_h2h_weight" % (d, layer),
                             "%s%d_i2h_bias" % (d, layer),
                             "%s%d_h2h_bias" % (d, layer)]
                    shapes = [(ng * H, in_size if in_size else 0),
                              (ng * H, H), (ng * H,), (ng * H,)]
                    inits = [i2h_weight_initializer, h2h_weight_initializer,
                             i2h_bias_initializer, h2h_bias_initializer]
                    for pname, shp, ini in zip(names, shapes, inits):
                        self.params.get(pname, shape=shp, init=ini,
                                        allow_deferred_init=True)
                    self._rnn_param_names.append(names)

    def _infer_param_shapes(self, x):
        input_size = x.shape[2]
        ng, H = self._gates, self._hidden_size
        for layer_names in self._rnn_param_names[: self._dir]:
            # only layer-0 i2h shapes depend on the input size
            p = self.params.get(layer_names[0])
            p.shape = (ng * H, input_size)

    def _fused_parameters(self):
        """Concatenate unfused params into the RNN op's flat layout:
        all (w_ih, w_hh) pairs, then all (b_ih, b_hh) pairs."""
        weights, biases = [], []
        for names in self._rnn_param_names:
            i2h_w, h2h_w, i2h_b, h2h_b = (self.params.get(n) for n in names)
            weights += [i2h_w.data().reshape((-1,)),
                        h2h_w.data().reshape((-1,))]
            biases += [i2h_b.data(), h2h_b.data()]
        return invoke("Concat", weights + biases, {"dim": 0})

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        states = []
        for info in self.state_info(batch_size):
            if func is None:
                states.append(nd.zeros(info["shape"], **kwargs))
            else:
                info.update(kwargs)
                states.append(func(name="%sh0" % self.prefix, **info))
        return states

    def forward(self, inputs, states=None):
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size, ctx=inputs.ctx, dtype=inputs.dtype)
        if isinstance(states, NDArray):
            states = [states]
        try:
            params = self._fused_parameters()
        except (DeferredInitializationError, MXNetError):
            self._infer_param_shapes(inputs)
            for names in self._rnn_param_names:
                for n in names:
                    p = self.params.get(n)
                    if p._data is None:
                        p._finish_deferred_init()
            params = self._fused_parameters()
        op_inputs = [inputs, params, states[0]]
        if self._mode == "lstm":
            op_inputs.append(states[1])
        outputs = invoke(
            "RNN", op_inputs,
            {
                "state_size": self._hidden_size, "num_layers": self._num_layers,
                "bidirectional": self._dir == 2, "mode": self._mode,
                "p": self._dropout, "state_outputs": True,
            },
        )
        if not isinstance(outputs, list):
            outputs = [outputs]
        out = outputs[0]
        out_states = outputs[1:]
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        if skip_states:
            return out
        return out, out_states

    def __repr__(self):
        s = "{name}({_hidden_size}, {_layout}, num_layers={_num_layers}"
        if self._dropout:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        return s.format(name=self.__class__.__name__, **self.__dict__)


class RNN(_RNNLayer):
    """Elman RNN (ref: rnn_layer.py RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu", layout="TNC",
                 dropout=0, bidirectional=False, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [
            {"shape": (self._num_layers * self._dir, batch_size, self._hidden_size), "__layout__": "LNC"},
            {"shape": (self._num_layers * self._dir, batch_size, self._hidden_size), "__layout__": "LNC"},
        ]


class GRU(_RNNLayer):
    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]
