"""Gluon Parameter / ParameterDict.

Reference counterpart: ``python/mxnet/gluon/parameter.py:43-581`` (deferred
shape init, per-ctx replicas, grad_req, constant params). TPU-native
design: one buffer per parameter (sharding across a mesh happens inside
compiled steps, not by replica lists); ``list_data``/``list_grad`` keep the
reference surface for multi-ctx call sites.
"""
from __future__ import annotations

import numpy as np

from .. import autograd
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import InitDesc, Initializer, create as create_init
from ..ndarray import ndarray as nd
from ..ndarray.ndarray import NDArray


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data = None
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req if differentiable else "null"
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self._shape, self.dtype)

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown = any(s == 0 for s in self._shape)
        if unknown:
            assert len(self._shape) == len(new_shape)
            merged = tuple(n if o == 0 else o for o, n in zip(self._shape, new_shape))
            self._shape = merged
        elif tuple(self._shape) != tuple(new_shape):
            raise MXNetError(
                "Parameter %s shape mismatch: %s vs %s" % (self.name, self._shape, new_shape)
            )

    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        if default_init is None:
            from ..initializer import Uniform

            default_init = Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if self._shape is None or any(s == 0 for s in self._shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init)
                return
            raise MXNetError(
                "Cannot initialize Parameter %s because it has invalid shape %s"
                % (self.name, self._shape)
            )
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        ctx0 = self._ctx_list[0]
        data = nd.zeros(self._shape, ctx=ctx0, dtype=self.dtype)
        initializer = init or self.init or default_init
        if isinstance(initializer, str):
            initializer = create_init(initializer)
        initializer(InitDesc(self.name), data)
        self._data = data
        if self.grad_req != "null":
            self._grad = nd.zeros(self._shape, ctx=ctx0, dtype=self.dtype)
            autograd.mark_variables([self._data], [self._grad], grad_reqs=self.grad_req)
        self._deferred_init = ()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init = self._deferred_init
        if self._shape is None or any(s == 0 for s in self._shape):
            raise DeferredInitializationError(
                "Parameter %s has unknown shape; run a forward pass first" % self.name
            )
        self._finish_init(init, default_init)

    def _check_initialized(self):
        if self._data is None:
            if self._deferred_init:
                raise DeferredInitializationError(
                    "Parameter %s was not initialized: deferred init pending first forward"
                    % self.name
                )
            raise MXNetError(
                "Parameter %s has not been initialized. Call initialize() first" % self.name
            )

    def data(self, ctx=None):
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad is None:
            raise MXNetError("Parameter %s does not have gradients (grad_req=null)" % self.name)
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None and self._deferred_init:
            return self._deferred_init[1]
        self._check_initialized()
        return self._ctx_list or [self._data.ctx]

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            if not self._deferred_init:
                raise MXNetError("Parameter %s not initialized" % self.name)
            self._finish_deferred_init()
        src = data if isinstance(data, NDArray) else nd.array(data)
        src.copyto(self._data)

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0

    def reset_ctx(self, ctx):
        pass  # single-buffer design; sharding handled in compiled steps

    def var(self):
        from .. import symbol as sym

        if self._var is None:
            self._var = sym.var(self.name, shape=self._shape, lr_mult=self.lr_mult,
                                wd_mult=self.wd_mult)
        return self._var

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data = self._data.astype(dtype)
            if self._grad is not None:
                self._grad = self._grad.astype(dtype)
                autograd.mark_variables([self._data], [self._grad], grad_reqs=self.grad_req)


class Constant(Parameter):
    """Non-updating parameter (ref: gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class _CInit(Initializer):
            def _init_weight(self2, _, arr):
                value.copyto(arr)

        super().__init__(name, grad_req="null", shape=value.shape, dtype=value.dtype,
                         init=_CInit())


class ParameterDict:
    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    def __repr__(self):
        s = "{name}(\n{content}\n)"
        name = self._prefix + " " if self._prefix else ""
        return s.format(
            name=name, content="\n".join(repr(v) for v in self._params.values())
        )

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if v is None:
                    continue
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape":
                        param.shape = v  # merges/validates unknown dims
                    elif k in ("init", "allow_deferred_init", "differentiable"):
                        continue
                    elif k == "dtype":
                        import numpy as _np

                        if _np.dtype(existing) != _np.dtype(v):
                            raise MXNetError(
                                "Parameter %s: inconsistent dtype %s vs existing %s"
                                % (name, v, existing)
                            )
                    elif existing != v:
                        raise MXNetError(
                            "Parameter %s: inconsistent attribute %s=%r vs existing %r"
                            % (name, k, v, existing)
                        )
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise MXNetError("No constant named %s" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("Cannot update self with other: duplicate key %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        if init is None:
            from ..initializer import Uniform

            init = Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        pass

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise MXNetError("Prefix %s is to be striped before saving, but Parameter "
                                 "%s does not start with %s" % (strip_prefix, param.name, strip_prefix))
            arg_dict[param.name[len(strip_prefix):]] = weight
        from ..ndarray.utils import save

        save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False, ignore_extra=False, restore_prefix=""):
        from ..ndarray.utils import load

        arg_dict = load(filename)
        arg_dict = {(restore_prefix + k): v for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise MXNetError("Parameter %s is missing in file %s" % (name, filename))
        for name in arg_dict:
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError("Parameter %s loaded from file %s is not present in this dict" % (name, filename))
                continue
            self[name]._load_init(arg_dict[name]) if hasattr(self[name], "_load_init") else self[name].set_data(arg_dict[name])
