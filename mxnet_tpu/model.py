"""Checkpointing + kvstore plumbing helpers.

Reference counterpart: ``python/mxnet/model.py`` — _create_kvstore (:58),
_initialize_kvstore, _update_params_on_kvstore (:126), save_checkpoint
(:366), load_checkpoint (:396). The two-artifact checkpoint format
(``prefix-symbol.json`` + ``prefix-%04d.params`` with ``arg:``/``aux:``
prefixed names) matches the reference so models interchange.
"""
from __future__ import annotations

import logging
from collections import namedtuple

from . import chaos
from . import kvstore as kvs
from . import symbol as sym_mod
from .base import MXNetError
from .ndarray.utils import load as nd_load, save as nd_save

BatchEndParam = namedtuple("BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore from spec (ref: model.py:58)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore and kvstore != "tpu":
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(param.shape) for param in arg_params.values()) if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise MXNetError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


import numpy as np  # noqa: E402  (used above lazily)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names, update_on_kvstore):
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """Push all grads, then pull all weights (ref: model.py:126 — push
    priority -idx so comm overlaps backprop; here the push-all phase lets
    a dist kvstore batch every key into one collective before the first
    pull flushes it, and XLA's async dispatch gives the overlap). On the
    async server tier the pushes enqueue onto the per-shard sender
    threads and return immediately; the ONE batched pull then waits on
    exactly those futures and fetches every weight in per-shard
    multi-key frames instead of a round trip per key."""
    # a worker "step" for deterministic fault injection = one optimizer
    # round (MXNET_FAULT_SPEC worker:R:crash@step=N, mxnet_tpu/chaos.py);
    # nan_fault is consulted FIRST (it targets the round about to run)
    poison = chaos.nan_fault()
    chaos.tick_step()
    live = []
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        if poison:
            # ISSUE 9 fault matrix: poison exactly ONE gradient — the
            # server-side optimizer then spreads the NaN into the
            # weight, the silent fault the fit health guard rolls back
            grad_list[0][:] = float("nan")
            poison = False
        kvstore.push(name, grad_list, priority=-index)
        live.append((index, name, arg_list))
    if live:
        kvstore.pull([name for _i, name, _a in live],
                     [arg_list for _i, _n, arg_list in live], priority=0)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None, param_names=None):
    poison = chaos.nan_fault()
    chaos.tick_step()  # same step definition as the kvstore path above
    live = []
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if poison:
            grad_list[0][:] = float("nan")  # ISSUE 9: poison ONE grad
            poison = False
        if kvstore:
            kvstore.push(param_names[i], grad_list, priority=-i)
        live.append((i, arg_list, grad_list))
    for index, arg_list, grad_list in live:
        if kvstore:
            kvstore.pull(param_names[index], grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save symbol + params (ref: model.py:366)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd_save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """Load symbol + params (ref: model.py:396)."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward:
    """Legacy estimator API (ref: model.py:434 FeedForward — deprecated
    in the reference in favor of Module, but still the surface its scala
    binding and many older scripts use). Implemented as a thin shell
    over :class:`mxnet_tpu.module.Module`: every fit/predict/score call
    delegates to the Module training loop, so both APIs share one
    compiled path."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from . import initializer as init_mod

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.optimizer_params = kwargs
        self._module = None

    # -- data normalization --------------------------------------------------
    def _as_iter(self, X, y=None, shuffle=False):
        from .io import DataIter, NDArrayIter

        if isinstance(X, DataIter):
            return X
        if y is None:
            y = np.zeros(len(X), dtype=np.float32)
        return NDArrayIter(np.asarray(X, np.float32),
                           np.asarray(y, np.float32),
                           batch_size=min(self.numpy_batch_size, len(X)),
                           shuffle=shuffle, label_name="softmax_label")

    def _get_module(self, data_iter, logger=None, work_load_list=None):
        from .module import Module

        if self._module is None:
            label_names = [d.name if hasattr(d, "name") else d[0]
                           for d in (data_iter.provide_label or [])]
            kw = {}
            if logger is not None:
                kw["logger"] = logger
            if work_load_list is not None:
                kw["work_load_list"] = work_load_list
            self._module = Module(self.symbol,
                                  data_names=[d.name if hasattr(d, "name")
                                              else d[0]
                                              for d in data_iter.provide_data],
                                  label_names=label_names or None,
                                  context=self.ctx, **kw)
        return self._module

    # -- estimator surface ---------------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        if self.num_epoch is None:
            raise MXNetError("FeedForward.fit: num_epoch was not set "
                             "(pass num_epoch= to the constructor)")
        train = self._as_iter(X, y, shuffle=True)
        if eval_data is not None and not hasattr(eval_data, "provide_data"):
            eval_data = self._as_iter(*eval_data) \
                if isinstance(eval_data, tuple) else self._as_iter(eval_data)
        mod = self._get_module(train, logger=logger,
                               work_load_list=work_load_list)
        opt_params = dict(self.optimizer_params)
        arg_params = self.arg_params
        if self.allow_extra_params and arg_params:
            known = set(self.symbol.list_arguments())
            arg_params = {k: v for k, v in arg_params.items() if k in known}
        # fused kvstore tiers get the async host→device input pipeline
        # (ISSUE 5): batches are sharded onto the mesh on a background
        # thread while the compiled step runs. Binding is deferred to the
        # first batch, i.e. after fit's init_optimizer built the group.
        kv_type = kvstore if isinstance(kvstore, str) \
            else getattr(kvstore, "type", "")
        pipelined = None
        if kv_type in ("tpu", "dist_sync", "dist_sync_device", "dist_async"):
            from .parallel.feed import DeviceQueueIter

            # close_source=False: the caller owns `train` and may fit()
            # again with it — only the wrapper's worker shuts down here
            train = pipelined = DeviceQueueIter(train, module=mod,
                                                close_source=False)
        try:
            mod.fit(train, eval_data=eval_data, eval_metric=eval_metric,
                    epoch_end_callback=epoch_end_callback,
                    batch_end_callback=batch_end_callback, kvstore=kvstore,
                    optimizer=self.optimizer, optimizer_params=opt_params,
                    eval_end_callback=eval_end_callback,
                    eval_batch_end_callback=eval_batch_end_callback,
                    initializer=self.initializer, arg_params=arg_params,
                    aux_params=self.aux_params, allow_missing=True,
                    begin_epoch=self.begin_epoch,
                    num_epoch=self.num_epoch, monitor=monitor,
                    force_rebind=True)  # a prior predict/score bound for inference
        finally:
            if pipelined is not None:
                pipelined.close()
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        it = self._as_iter(X)
        mod = self._get_module(it)
        if not mod.binded:
            mod.bind(data_shapes=it.provide_data,
                     label_shapes=it.provide_label, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=False)
        if not return_data:
            outs = mod.predict(it, num_batch=num_batch, reset=reset)
            out = outs[0] if isinstance(outs, list) and len(outs) == 1 else outs
            return out.asnumpy() if hasattr(out, "asnumpy") else out
        # reference return_data mode: (outputs, datas, labels)
        if reset:
            it.reset()
        outs, datas, labels = [], [], []
        for i, batch in enumerate(it):
            if num_batch is not None and i >= num_batch:
                break
            mod.forward(batch, is_train=False)
            n = batch.data[0].shape[0] - (batch.pad or 0)
            outs.append(mod.get_outputs()[0].asnumpy()[:n])
            datas.append(batch.data[0].asnumpy()[:n])
            if batch.label:
                labels.append(batch.label[0].asnumpy()[:n])
        cat = np.concatenate
        return (cat(outs), cat(datas), cat(labels) if labels else None)

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        from . import metric as metric_mod

        it = self._as_iter(X)
        mod = self._get_module(it)
        if not mod.binded:
            mod.bind(data_shapes=it.provide_data,
                     label_shapes=it.provide_label, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {})
        m = metric_mod.create(eval_metric)
        mod.score(it, m, num_batch=num_batch, reset=reset,
                  batch_end_callback=batch_end_callback)
        # composite metrics return a list of values (ref model.py score)
        _, value = m.get()
        return value

    # -- persistence (two-artifact checkpoint format) ------------------------
    def save(self, prefix, epoch=None):
        epoch = self.num_epoch if epoch is None else epoch
        save_checkpoint(prefix, epoch or 0, self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Train a new model from scratch (ref: model.py:930)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
