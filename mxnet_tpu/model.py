"""Checkpointing + kvstore plumbing helpers.

Reference counterpart: ``python/mxnet/model.py`` — _create_kvstore (:58),
_initialize_kvstore, _update_params_on_kvstore (:126), save_checkpoint
(:366), load_checkpoint (:396). The two-artifact checkpoint format
(``prefix-symbol.json`` + ``prefix-%04d.params`` with ``arg:``/``aux:``
prefixed names) matches the reference so models interchange.
"""
from __future__ import annotations

import logging
from collections import namedtuple

from . import kvstore as kvs
from . import symbol as sym_mod
from .base import MXNetError
from .ndarray.utils import load as nd_load, save as nd_save

BatchEndParam = namedtuple("BatchEndParams", ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore from spec (ref: model.py:58)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore and kvstore != "tpu":
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(param.shape) for param in arg_params.values()) if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise MXNetError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return (kv, update_on_kvstore)


import numpy as np  # noqa: E402  (used above lazily)


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names, update_on_kvstore):
    for idx, param_on_devs in enumerate(param_arrays):
        name = param_names[idx]
        kvstore.init(name, arg_params[name])
        if update_on_kvstore:
            kvstore.pull(name, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore, param_names):
    """Push all grads, then pull all weights (ref: model.py:126 — push
    priority -idx so comm overlaps backprop; here the push-all phase lets
    a dist kvstore batch every key into one collective before the first
    pull flushes it, and XLA's async dispatch gives the overlap)."""
    live = []
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        name = param_names[index]
        kvstore.push(name, grad_list, priority=-index)
        live.append((index, name, arg_list))
    for index, name, arg_list in live:
        kvstore.pull(name, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None, param_names=None):
    live = []
    for i, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if kvstore:
            kvstore.push(param_names[i], grad_list, priority=-i)
        live.append((i, arg_list, grad_list))
    for index, arg_list, grad_list in live:
        if kvstore:
            kvstore.pull(param_names[index], grad_list, priority=-index)
        for k, p in enumerate(zip(arg_list, grad_list)):
            w, g = p
            updater(index * num_device + k, g, w)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """Save symbol + params (ref: model.py:366)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd_save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_checkpoint(prefix, epoch):
    """Load symbol + params (ref: model.py:396)."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)
