"""Base utilities: error types, name management, type coercion.

TPU-native re-imagination of the reference's ``python/mxnet/base.py`` —
instead of a ctypes bridge to a C ABI (ref: python/mxnet/base.py:452-584),
the front end talks directly to the in-process op registry
(:mod:`mxnet_tpu.ops.registry`); op namespaces (``_contrib_``, ``_linalg_``,
``_random_``) are materialized into python modules the same way the
reference's ``_init_op_module`` does.
"""
from __future__ import annotations

import re
import threading

import numpy as np


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: python/mxnet/base.py MXNetError)."""


string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)

_GLOBAL_NAME_LOCK = threading.Lock()


class _NameCounter:
    """Per-prefix monotonically increasing counters for auto-naming.

    Parity with NameManager (ref: python/mxnet/name.py): symbols and gluon
    blocks get names like ``convolution0``, ``convolution1``.
    """

    def __init__(self):
        self._counts = {}

    def get(self, prefix: str) -> str:
        with _GLOBAL_NAME_LOCK:
            idx = self._counts.get(prefix, 0)
            self._counts[prefix] = idx + 1
        return "%s%d" % (prefix, idx)

    def reset(self):
        with _GLOBAL_NAME_LOCK:
            self._counts.clear()


_NAME_COUNTER = _NameCounter()


def auto_name(prefix: str) -> str:
    # route through an active mx.name.NameManager/Prefix scope if any
    import sys

    name_mod = sys.modules.get("mxnet_tpu.name")
    if name_mod is not None:
        return name_mod._auto_name(prefix)
    return _NAME_COUNTER.get(prefix.lower())


def reset_naming():
    _NAME_COUNTER.reset()


_DTYPE_ALIASES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": None,  # resolved lazily to ml_dtypes bfloat16 via jnp
    "uint8": np.uint8,
    "int8": np.int8,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def dtype_np(dtype):
    """Normalize a dtype spec (string/np.dtype/jnp dtype) to a numpy dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            import jax.numpy as jnp

            return jnp.bfloat16
        got = _DTYPE_ALIASES.get(dtype)
        if got is None:
            raise MXNetError("unknown dtype %r" % (dtype,))
        return np.dtype(got)
    return np.dtype(dtype) if not _is_bfloat16(dtype) else dtype


def _is_bfloat16(dtype) -> bool:
    return getattr(dtype, "__name__", None) == "bfloat16" or str(dtype) == "bfloat16"


def dtype_name(dtype) -> str:
    """Canonical string name for a dtype."""
    if isinstance(dtype, str):
        return dtype
    return np.dtype(dtype).name if not _is_bfloat16(dtype) else "bfloat16"


_PYTHONIC = re.compile(r"[^0-9a-zA-Z_]")


def sanitize_name(name: str) -> str:
    return _PYTHONIC.sub("_", name)


def check_call(ret):
    """Parity shim — there is no C ABI; errors are python exceptions."""
    return ret


def classproperty(func):
    class _Desc:
        def __get__(self, _obj, objtype=None):
            return func(objtype)

    return _Desc()
