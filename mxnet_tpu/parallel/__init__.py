"""Parallelism: device meshes, sharded training steps, collectives.

This package is the TPU-native answer to the reference's src/kvstore comm
stack (SURVEY §2.4): parallelism is expressed as jax.sharding over a Mesh
and compiled into the training step, not as a runtime service. Beyond the
reference's data parallelism it adds the TPU generalizations the survey
mandates: ring-attention/Ulysses sequence parallelism (ring.py) and a
GPipe collective-permute pipeline (pipeline.py).
"""
from .feed import DeviceQueueIter, place_batch_array  # noqa: F401
from .mesh import default_mesh, make_mesh, set_default_mesh  # noqa: F401
from .ring import (  # noqa: F401
    full_attention, ring_attention, ring_attention_inner,
    ulysses_attention, ulysses_attention_inner,
)
from .pipeline import pipeline, pipeline_apply  # noqa: F401
