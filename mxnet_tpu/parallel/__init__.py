"""Parallelism: device meshes, sharded training steps, collectives.

This package is the TPU-native answer to the reference's src/kvstore comm
stack (SURVEY §2.4): parallelism is expressed as jax.sharding over a Mesh
and compiled into the training step, not as a runtime service.
"""
from .mesh import default_mesh, make_mesh  # noqa: F401
