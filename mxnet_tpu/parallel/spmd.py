"""SPMD fused training step: loss + grad + optimizer update in ONE XLA program.

Reference counterpart: the hot path assembled from
``DataParallelExecutorGroup`` (python/mxnet/module/executor_group.py:128 —
batch split across devices), ``Comm::Reduce``/KVStore push-pull gradient
sync (src/kvstore/comm.h:56, kvstore_local.h), and the ``sgd_mom_update``
CUDA kernels (src/operator/optimizer_op.cc:39-286). TPU-native design: all
three stages fuse into a single ``jax.jit`` program over a
``jax.sharding.Mesh`` —

- batch arrays are sharded over the data axes (``dp``); XLA inserts the
  gradient all-reduce (psum over ICI) where the reference ran NCCL/ps-lite,
  and overlaps it with backprop via its latency-hiding scheduler (the
  reference's priority-queue overlap, model.py:126-137).
- parameters may be sharded over ``tp`` (tensor parallel) by regex rules —
  the generalization of the reference's `group2ctx` model parallelism.
- the optimizer update runs on the sharded gradients in the same program
  (no separate push/pull round trip); with weight-update sharding
  (`zero=True`) each dp-shard updates a slice of the weights and
  all-gathers — the ZeRO analogue of the reference's server-side optimizer
  (kvstore_dist_server.h set_optimizer).
- mixed precision: master weights fp32, compute in ``compute_dtype``
  (bfloat16 on the MXU) — the mp_sgd_* multi-precision pattern
  (src/operator/optimizer_op.cc mp_sgd_update) without a separate kernel.

This module is pure-functional (params/states are pytrees, not NDArrays):
it is the engine under ``kvstore='tpu'`` Module training, ``bench.py`` and
``__graft_entry__.py``.
"""
from __future__ import annotations

import contextlib
import functools
import re

import jax
import jax.numpy as jnp
import numpy as _np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = [
    "param_shardings", "data_sharding", "replicated", "make_train_step",
    "TrainStep", "functional_optimizer", "functional_from_optimizer",
    "cross_entropy_loss", "parse_rules", "ShardingRuleError",
]

# Primitives whose outputs the remat="conv" policy SAVES. The fused
# Pallas units trace as custom_vjp/jvp call primitives (on CPU
# reference too), and pallas_call is what a kernel lowers to when the
# custom-vjp wrapper is absent — without these, a fused ResNet under
# remat="conv" recomputes its most expensive kernels in backward, the
# exact ops the policy exists to save (ISSUE 19 bugfix).
_SAVEABLE_PRIMS = (
    "conv_general_dilated",
    "dot_general",
    "pallas_call",
    "custom_vjp_call",
    "custom_vjp_call_jaxpr",
    "custom_jvp_call",
    "custom_jvp_call_jaxpr",
)


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------
def replicated(mesh):
    return NamedSharding(mesh, P())


def data_sharding(mesh, axes=("dp",), ndim=None):
    """Shard the leading (batch) dimension over the given mesh axes."""
    names = [a for a in axes if a in mesh.axis_names]
    spec = P(tuple(names)) if names else P()
    return NamedSharding(mesh, spec)


class ShardingRuleError(MXNetError):
    """A parameter-sharding rule matched but cannot apply: the spec
    names a mesh axis the mesh does not have, or a sharded dim is not
    divisible by the axis size. Raised instead of silently replicating
    (ISSUE 20) — a silently replicated layer would defeat the 1/mp
    per-chip memory claim while looking healthy."""


def param_shardings(params, mesh, rules=None):
    """Map param name -> NamedSharding via ordered (regex, PartitionSpec)
    rules; first match wins, default replicated.

    Example rules for megatron-style tensor parallelism::

        [(r".*ffn_up_weight",  P("mp", None)),   # (out, in): shard out dim
         (r".*ffn_down_weight", P(None, "mp")),
         (r".*", P())]

    A matched rule that cannot apply — the spec names an axis the mesh
    does not have, or the sharded dim is not divisible by the axis
    size — raises :class:`ShardingRuleError` naming the parameter and
    the rule.
    """
    rules = rules or []
    out = {}
    for name, v in params.items():
        spec = P()
        rule_pat = None
        for pat, s in rules:
            if re.match(pat, name):
                spec = s if isinstance(s, P) else P(*s)
                rule_pat = pat
                break
        if spec != P():
            problem = _spec_misfit(spec, v.shape, mesh)
            if problem is not None:
                raise ShardingRuleError(
                    "param_shardings: rule (%r, %s) matched parameter "
                    "%r with shape %s but cannot apply: %s"
                    % (rule_pat, spec, name, tuple(v.shape), problem))
        out[name] = NamedSharding(mesh, spec)
    return out


def _spec_misfit(spec, shape, mesh):
    """None iff every axis in spec exists on the mesh and divides its
    dim; otherwise a human-readable reason string."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    spec_t = tuple(spec)
    if len(spec_t) > len(shape):
        return ("spec has %d entries for a %d-dim shape"
                % (len(spec_t), len(shape)))
    for dim, ax in zip(shape, spec_t + (None,) * (len(shape) - len(spec_t))):
        if ax is None:
            continue
        axs = (ax,) if isinstance(ax, str) else tuple(ax)
        n = 1
        for a in axs:
            if a not in sizes:
                return ("mesh has no axis %r (mesh axes: %s)"
                        % (a, ", ".join(sizes) or "<none>"))
            n *= sizes[a]
        if dim % n != 0:
            return ("dim %d is not divisible by the axis size %d"
                    % (dim, n))
    return None


def parse_rules(text, knob="MXNET_MP_RULES"):
    """Parse the ``MXNET_MP_RULES`` grammar ``'regex:spec;regex:spec'``
    into the ordered ``[(regex, PartitionSpec)]`` list
    :func:`param_shardings` consumes. ``spec`` is a comma list with one
    entry per dim: ``*`` replicates that dim, anything else is a
    mesh-axis name (existence/divisibility are checked at apply time by
    :func:`param_shardings`, which raises :class:`ShardingRuleError`).
    Malformed grammar raises :class:`MXNetError` naming the knob."""
    rules = []
    text = (text or "").strip()
    if not text:
        return rules
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        # rpartition: the regex may contain ':' (e.g. char classes),
        # the spec never does
        pat, sep, spec_s = part.rpartition(":")
        pat = pat.strip()
        if not sep or not pat:
            raise MXNetError(
                "%s: rule %r must be 'regex:spec' with spec a comma "
                "list of '*' or mesh-axis names" % (knob, part))
        try:
            re.compile(pat)
        except re.error as e:
            raise MXNetError(
                "%s: bad regex %r in rule %r: %s" % (knob, pat, part, e))
        entries = []
        for ent in spec_s.split(","):
            ent = ent.strip()
            if not ent:
                raise MXNetError(
                    "%s: empty spec entry in rule %r (use '*' to "
                    "replicate a dim)" % (knob, part))
            entries.append(None if ent == "*" else ent)
        rules.append((pat, P(*entries)))
    return rules


# ---------------------------------------------------------------------------
# functional optimizers (pure mirrors of optimizer.py classes, built on the
# registered pure-JAX update ops in ops/optimizer_ops.py)
# ---------------------------------------------------------------------------
class FunctionalOptimizer:
    """init(params)->state pytree; apply(params, grads, state, step)->new."""

    def __init__(self, init, apply, hyper=None):
        self.init = init
        self.apply = apply
        self.hyper = dict(hyper or {})


def functional_optimizer(name="sgd", learning_rate=0.01, momentum=0.0, wd=0.0,
                         beta1=0.9, beta2=0.999, epsilon=1e-8,
                         rescale_grad=1.0, clip_gradient=None,
                         lr_scheduler=None, wd_pattern=r".*(weight|gamma)$",
                         lr_mult=None, wd_mult=None):
    """Build a pure optimizer. ``wd_pattern``: params matching get weight
    decay, others (bias/beta/moving stats) get 0 — set_wd_mult parity
    (python/mxnet/optimizer.py set_wd_mult). Explicit per-name ``lr_mult``
    / ``wd_mult`` dicts (default multiplier 1.0) override the pattern,
    mirroring Optimizer.set_lr_mult/set_wd_mult exactly."""
    name = name.lower()
    wd_re = re.compile(wd_pattern)

    def lr_at(step):
        if lr_scheduler is not None:
            return lr_scheduler(step)
        return learning_rate

    def mults(k):
        lm = 1.0 if lr_mult is None else float(lr_mult.get(k, 1.0))
        if wd_mult is not None:
            wm = wd * float(wd_mult.get(k, 1.0))
        else:
            wm = wd if wd_re.match(k) else 0.0
        return lm, wm

    def preprocess(g):
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient is not None and clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        return g

    if name == "sgd":
        def init(params):
            if momentum == 0.0:
                return {}
            return {k: jnp.zeros_like(v) for k, v in params.items()}

        def apply(params, grads, state, step):
            lr = lr_at(step)
            new_p, new_s = {}, {}
            for k, w in params.items():
                g = preprocess(grads[k])
                lm, this_wd = mults(k)
                g = g + this_wd * w
                if momentum == 0.0:
                    new_p[k] = w - (lr * lm) * g
                else:
                    m = momentum * state[k] - (lr * lm) * g
                    new_s[k] = m
                    new_p[k] = w + m
            return new_p, new_s

        return FunctionalOptimizer(init, apply, dict(lr=learning_rate, momentum=momentum, wd=wd))

    if name == "adam":
        def init(params):
            return {
                k: (jnp.zeros_like(v), jnp.zeros_like(v)) for k, v in params.items()
            }

        def apply(params, grads, state, step):
            lr = lr_at(step)
            t = step.astype(jnp.float32) + 1.0
            coef1 = 1.0 - beta1 ** t
            coef2 = 1.0 - beta2 ** t
            lr_t = lr * jnp.sqrt(coef2) / coef1
            new_p, new_s = {}, {}
            for k, w in params.items():
                g = preprocess(grads[k])
                lm, this_wd = mults(k)
                g = g + this_wd * w
                m, v = state[k]
                m = beta1 * m + (1 - beta1) * g
                v = beta2 * v + (1 - beta2) * g * g
                new_s[k] = (m, v)
                new_p[k] = w - (lr_t * lm) * m / (jnp.sqrt(v) + epsilon)
            return new_p, new_s

        return FunctionalOptimizer(init, apply, dict(lr=learning_rate, wd=wd))

    raise MXNetError("functional_optimizer: unknown optimizer %r" % name)


def functional_from_optimizer(opt, param_names):
    """Map an imperative ``optimizer.Optimizer`` instance to the pure
    FunctionalOptimizer used by the fused SPMD step (Module kvstore='tpu').

    Raises MXNetError for optimizers/features the fused path cannot
    reproduce exactly (callers fall back to per-executor update).
    """
    from .. import optimizer as opt_mod

    if opt.lr_scheduler is not None:
        raise MXNetError(
            "fused SPMD step: lr_scheduler uses python control flow per "
            "update and cannot be traced; falling back")
    if getattr(opt, "param_dict", None):
        raise MXNetError("fused SPMD step: param_dict mults not supported")
    lr_mult = {n: opt.lr_mult.get(n, 1.0) for n in param_names}
    wd_mult = {n: opt.wd_mult.get(n, 1.0) for n in param_names}
    common = dict(
        learning_rate=opt.lr, wd=opt.wd, rescale_grad=opt.rescale_grad,
        clip_gradient=opt.clip_gradient, lr_mult=lr_mult, wd_mult=wd_mult,
    )
    if type(opt) is opt_mod.SGD:
        return functional_optimizer("sgd", momentum=opt.momentum, **common)
    if type(opt) is opt_mod.Adam:
        return functional_optimizer(
            "adam", beta1=opt.beta1, beta2=opt.beta2, epsilon=opt.epsilon, **common)
    raise MXNetError(
        "fused SPMD step: optimizer %s has no functional mirror"
        % type(opt).__name__)


def cross_entropy_loss(probs, label, eps=1e-12):
    """Mean CE given probabilities (SoftmaxOutput forward emits probs)."""
    lbl = label.astype(jnp.int32).reshape(-1)
    p = probs.reshape(lbl.shape[0], -1)
    picked = jnp.take_along_axis(p, lbl[:, None], axis=-1)
    return -jnp.mean(jnp.log(picked + eps))


# ---------------------------------------------------------------------------
# the fused train step
# ---------------------------------------------------------------------------
class TrainStep:
    """Compiled SPMD training step for a Symbol graph.

    step(carry, batch) -> (carry, loss); carry = (params, opt_state,
    aux, step_no), all device-resident and donated between steps.

    Gradient semantics: gradients flow through the graph exactly as the
    reference's ``Executor::Backward`` with ones head-grads — fused loss
    heads (SoftmaxOutput & co.) substitute their own backward
    (sum-CE gradient), so for such graphs ``loss_fn`` only affects the
    *reported* loss, not the gradients (reference parity:
    src/operator/softmax_output.cc discards out_grad unless out_grad=True).
    ``normalize_grads=True`` (default) divides gradients by global batch
    size, mirroring Module's ``rescale_grad=1/batch`` convention so lr
    values transfer.

    ``zero=True`` (default: the ``MXNET_TPU_ZERO`` knob) turns on
    weight-update sharding (ZeRO / arXiv:2004.13336 — the TPU answer to
    the reference's server-side optimizer, kvstore_dist_server.h): each
    large replicated parameter's update is computed on an explicit
    ``(num_shards, chunk)`` view of its flattened (zero-padded) value,
    with the gradient view constrained to the data axes — the
    reduce-scatter point: XLA materializes each device's 1/N gradient
    shard directly instead of all-reducing the full gradient — the
    optimizer update runs on that 1/N shard (momentum/Adam state lives
    ONLY in its shard between steps, so per-device optimizer-state
    bytes scale 1/N), and the updated shards are constrained back to
    replicated — the all-gather point. Collective volume equals the
    plain all-reduce (RS+AG == AR); memory and update FLOPs drop to
    1/N. Parameters smaller than ``MXNET_TPU_ZERO_MIN_SIZE`` elements
    and tensor-parallel-sharded parameters keep the mirrored path.
    Uneven sizes (``size % N != 0``) are zero-padded; the padding lanes
    provably stay zero under sgd/momentum/adam + wd. With
    ``zero_wire="2bit"`` (``MXNET_TPU_ZERO_WIRE``) the reduce-scattered
    gradient shard additionally round-trips through the PR 4 packed
    two-bit wire codes with a 1/N-sharded error-feedback residual
    (multi-host: this is the quantizer sitting on the reduce-scattered
    DCN wire; single-host: the exact-fidelity simulation, like the
    local tier). The residual is transient — it resets on
    checkpoint restore, matching the server tier's residuals.

    ``sentinel`` (default: the ``MXNET_TPU_SENTINEL`` knob) arms the
    IN-GRAPH anomaly sentinel (ISSUE 9): every step computes a health
    word INSIDE the compiled program — finite loss, finite global
    gradient norm (the grads here are already the mesh-global psum'd
    sums, so the word is identical on every device/host by
    construction), and all-finite updated params — and folds it into
    device-resident counters riding the carry's opt_state under a
    reserved key (the PR 5 device-accumulator pattern: zero per-batch
    host syncs in ``record``/``skip``). ``skip`` additionally turns an
    unhealthy step into a no-op: the pre-update params, optimizer
    state and aux are selected back via ``jnp.where`` (bit-identical
    params, step counter not advanced) and the skip is counted.
    ``halt`` reads the health word on host after EVERY step (the one
    per-batch-sync mode, counted in ``host_syncs``) and raises on the
    first unhealthy step. The counters are transient like the 2-bit
    wire residual: dropped from checkpoints, fresh zeros on restore.
    Drain them with :meth:`health_stats`.

    ``metric_stats=True`` (requires ``return_outputs=True``) additionally
    returns a dict of replicated per-batch metric statistics computed
    INSIDE the compiled program — ``n`` (rows), ``sum_loss`` (loss·n),
    and, for a 2-D first output with a 1-D label, ``correct`` (argmax
    match count) and ``sum_ce`` (summed -log p[label], eps 1e-12,
    mirroring metric.CrossEntropy). The fit loop accumulates these on
    device so no per-batch host sync is needed to keep metrics
    (ISSUE 5 device-resident metrics). Step returns become
    ``(carry, (loss, outputs, stats))``.
    """

    def __init__(self, symbol, optimizer, mesh=None, data_axes=("dp",),
                 param_rules=None, label_names=("softmax_label",),
                 data_names=("data",), compute_dtype=None, loss_fn=None,
                 zero=None, remat=None, normalize_grads=True,
                 return_outputs=False, metric_stats=False, zero_wire=None,
                 zero_min_size=None, sentinel=None, train_passes=None):
        from .. import config
        from ..executor import _graph_closure

        # ISSUE 19: training-graph pass pipeline — explicit arg wins,
        # None consults MXNET_IR_TRAIN_PASSES; names are validated
        # against the ir.PASSES registry by apply_passes. The rewritten
        # symbol IS self.symbol: shapes/params/remat plan all follow it.
        if train_passes is None:
            raw = config.get("MXNET_IR_TRAIN_PASSES")
            train_passes = tuple(
                p.strip() for p in str(raw).split(",") if p.strip())
        elif isinstance(train_passes, str):
            train_passes = tuple(
                p.strip() for p in train_passes.split(",") if p.strip())
        else:
            train_passes = tuple(str(p).strip() for p in train_passes
                                 if str(p).strip())
        self.train_passes = train_passes
        if train_passes:
            from ..ir import apply_passes

            symbol = apply_passes(symbol, list(train_passes))
        self.symbol = symbol
        self.mesh = mesh
        self.data_axes = tuple(data_axes)
        # ZeRO knobs (ISSUE 7): explicit ctor args win; None consults the
        # env knobs, which are strictly validated (nonsense raises)
        if zero is None:
            zero = config.get_strict_bool("MXNET_TPU_ZERO")
        self.zero = bool(zero)
        if zero_wire is None:
            zero_wire = config.get_choice("MXNET_TPU_ZERO_WIRE",
                                          ("raw", "2bit"))
        elif zero_wire not in ("raw", "2bit"):
            raise MXNetError("TrainStep: zero_wire=%r must be raw|2bit"
                             % (zero_wire,))
        self.zero_wire = zero_wire
        self.zero_threshold = config.get_positive_float(
            "MXNET_TPU_ZERO_WIRE_THRESHOLD")
        if zero_min_size is None:
            zero_min_size = config.get_nonneg_int("MXNET_TPU_ZERO_MIN_SIZE")
        self.zero_min_size = int(zero_min_size)
        # ISSUE 9: in-graph anomaly sentinel — explicit arg wins, else
        # the strictly-validated knob (nonsense raises at construction)
        if sentinel is None:
            sentinel = config.get_choice("MXNET_TPU_SENTINEL",
                                         ("off", "record", "skip", "halt"))
        elif sentinel not in ("off", "record", "skip", "halt"):
            raise MXNetError("TrainStep: sentinel=%r must be "
                             "off|record|skip|halt" % (sentinel,))
        self.sentinel = sentinel
        self.optimizer = (
            optimizer if isinstance(optimizer, FunctionalOptimizer)
            else functional_optimizer(**optimizer) if isinstance(optimizer, dict)
            else functional_optimizer(optimizer)
        )
        self.label_names = tuple(label_names)
        self.data_names = tuple(data_names)
        self.compute_dtype = compute_dtype
        self.loss_fn = loss_fn or cross_entropy_loss
        # ISSUE 19: remat — explicit arg wins; None consults the
        # strictly-validated MXNET_TPU_REMAT knob. False/off: no remat;
        # True: full recompute; "conv": prim-name policy; "pass": the
        # per-site IR plan (ir/remat.py) via named checkpointing.
        if remat is None:
            raw = config.get_choice("MXNET_TPU_REMAT",
                                    ("0", "1", "off", "conv", "pass"))
            remat = {"0": False, "off": False, "1": True}.get(raw, raw)
        elif remat not in (False, True, "conv", "pass"):
            raise MXNetError(
                "TrainStep: remat=%r must be False|True|'conv'|'pass'"
                % (remat,))
        self.remat = remat
        self.normalize_grads = normalize_grads
        self.return_outputs = return_outputs
        if metric_stats and not return_outputs:
            raise MXNetError(
                "TrainStep: metric_stats=True requires return_outputs=True")
        self.metric_stats = metric_stats
        self.param_rules = list(param_rules or [])

        arg_names = symbol.list_arguments()
        self.param_names = [
            n for n in arg_names if n not in self.data_names and n not in self.label_names
        ]
        self.aux_names = symbol.list_auxiliary_states()
        # ISSUE 19: remat="pass" plans save/recompute per NODE and the
        # closure tags each to-save node's outputs with checkpoint_name;
        # every other mode builds the tag-free closure (bit-identical
        # graphs to the pre-pass behavior).
        self._remat_plan = None
        remat_names = None
        if self.remat == "pass":
            from ..ir.remat import plan_remat

            self._remat_plan = plan_remat(symbol)
            remat_names = frozenset(self._remat_plan.save)
        self._graph = _graph_closure(symbol, is_train=True,
                                     remat_names=remat_names)
        self._step_fn = None
        self._jit_fn = None

    # -- initialization ------------------------------------------------------
    def init_params(self, data_shapes, initializer=None, dtype=_np.float32, seed=0):
        """Infer shapes from data shapes and initialize params/aux.

        All allocation happens on the target mesh's first device (or the
        process default when no mesh is set) so that a mesh built from
        non-default devices — e.g. the 8-CPU-device dryrun mesh while the
        default platform is a TPU — never touches the default device.
        """
        from ..initializer import Uniform, InitDesc

        shape_kwargs = dict(data_shapes)
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**shape_kwargs)
        arg_names = self.symbol.list_arguments()
        init = initializer or Uniform(0.01)
        params, aux = {}, {}
        dev = None
        if self.mesh is not None:
            # First *addressable* device: in a multi-host mesh, devices.flat[0]
            # may belong to another process and cannot host allocations.
            pidx = jax.process_index()
            dev = next((d for d in self.mesh.devices.flat if d.process_index == pidx), None)
        ctx = jax.default_device(dev) if dev is not None else contextlib.nullcontext()
        np_state = _np.random.get_state()
        _np.random.seed(seed)
        # the initializer zoo draws from the module-owned RNG
        # (random.initializer_rng), not the global numpy one — seed it
        # too, else same-seed init_params differs across processes
        from .. import random as _rnd_mod

        prev_init_rng = _rnd_mod._INIT_RNG
        _rnd_mod._INIT_RNG = _np.random.RandomState(int(seed) & 0x7FFFFFFF)
        try:
            with ctx:
                for name, shape in zip(arg_names, arg_shapes):
                    if name in self.data_names or name in self.label_names:
                        continue
                    from ..ndarray.ndarray import zeros as nd_zeros

                    arr = nd_zeros(shape, dtype=dtype)
                    init(InitDesc(name), arr)
                    params[name] = arr._data()
                for name, shape in zip(self.aux_names, aux_shapes):
                    val = jnp.ones(shape, dtype) if "var" in name or "gamma" in name else jnp.zeros(shape, dtype)
                    aux[name] = val
                opt_state = self.optimizer.init(params)
        finally:
            _np.random.set_state(np_state)
            _rnd_mod._INIT_RNG = prev_init_rng
        return params, opt_state, aux

    # -- weight-update sharding (ZeRO, ISSUE 7) ------------------------------
    def _zero_axes(self):
        """Mesh axes the weight update shards over (the data axes)."""
        if not self.zero or self.mesh is None:
            return ()
        return tuple(a for a in self.data_axes if a in self.mesh.axis_names)

    def zero_plan(self, params, param_rules=None):
        """{param_name: (shape, size, num_shards, chunk)} for every
        parameter whose update shards over the data axes: replicated by
        the tp rules, at least ``zero_min_size`` (and ``num_shards``)
        elements. Empty when zero is off or the mesh has one device."""
        axes = self._zero_axes()
        if not axes:
            return {}
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        n = 1
        for a in axes:
            n *= sizes[a]
        if n <= 1:
            return {}
        rules = self.param_rules if param_rules is None else param_rules
        ps = param_shardings(params, self.mesh, rules)
        plan = {}
        for k, v in params.items():
            shape = tuple(v.shape)
            if not shape or ps[k].spec != P():
                continue  # scalars and tp-sharded params keep mirrors
            size = 1
            for d in shape:
                size *= int(d)
            if size < max(self.zero_min_size, n):
                continue
            plan[k] = (shape, size, n, -(-size // n))
        return plan

    _ZERO_RES = "__zero_wire_residual__"
    _SENT = "__sentinel_state__"

    @staticmethod
    def _sentinel_init():
        """Fresh device-resident sentinel counters (replicated int32/
        float32 scalars riding opt_state under the reserved key)."""
        z = _np.int32(0)
        return {"healthy": z, "unhealthy": z, "skipped": z, "consec": z,
                "nonfinite_loss": z, "nonfinite_grad": z,
                "nonfinite_param": z, "last_healthy": _np.int32(1),
                "last_loss": _np.float32(0.0)}

    def _ensure_sentinel(self, opt_state):
        """Reconcile the reserved sentinel-counter key with the mode:
        created when armed and missing (idempotent — live counters on
        a re-placed carry survive), dropped when off."""
        if self.sentinel == "off":
            if self._SENT in opt_state:
                opt_state = {k: v for k, v in opt_state.items()
                             if k != self._SENT}
            return opt_state
        if self._SENT in opt_state:
            return opt_state
        out = dict(opt_state)
        out[self._SENT] = self._sentinel_init()
        return out

    @staticmethod
    def _zsplit_np(x, n, chunk):
        """Host-side logical → (num_shards, chunk) zero layout."""
        flat = _np.asarray(x).reshape(-1)
        pad = n * chunk - flat.size
        if pad:
            flat = _np.concatenate([flat, _np.zeros((pad,), flat.dtype)])
        return flat.reshape(n, chunk)

    def _opt_state_to_zero(self, opt_state, plan):
        """Lay optimizer state out for the sharded update: every array
        leaf of a planned param becomes its padded (num_shards, chunk)
        view, and the 2-bit wire residual tree is created when missing.
        Idempotent — leaves already in zero layout pass through, so
        re-placing a live carry (set_params/_replace) is a no-op."""
        if not plan:
            return opt_state
        out = {}
        for k, v in opt_state.items():
            if k == self._ZERO_RES:
                out[k] = v  # live residual: keep it across re-places
                continue
            if k not in plan:
                out[k] = v
                continue
            _shape, _size, n, chunk = plan[k]
            out[k] = jax.tree_util.tree_map(
                lambda x: x if tuple(getattr(x, "shape", ())) == (n, chunk)
                else self._zsplit_np(x, n, chunk), v)
        if self.zero_wire == "2bit":
            # reconcile the residual tree with THIS plan: keep live
            # per-key residuals whose shard shape still matches, zero
            # the rest (a rules change mid-life alters the plan; a
            # stale residual key would KeyError inside the step)
            res = out.get(self._ZERO_RES) or {}
            out[self._ZERO_RES] = {
                k: res[k] if (k in res and tuple(_np.shape(res[k]))
                              == (plan[k][2], plan[k][3]))
                else _np.zeros((plan[k][2], plan[k][3]), _np.float32)
                for k in plan}
        elif self._ZERO_RES in out:
            del out[self._ZERO_RES]  # wire turned off: drop residuals
        return out

    def logical_opt_state(self, opt_state, params, param_rules=None):
        """Zero-layout (host) optimizer state → the mesh-size-independent
        logical layout checkpoints store: planned leaves are un-padded
        and reshaped back to their parameter's shape; the transient wire
        residual is dropped (it resets on restore, like the server
        tier's residuals). The inverse of :meth:`_opt_state_to_zero`, so
        a state saved under ``zero=True`` on N devices restores bit-
        exactly under ``zero=False`` or any other mesh size."""
        plan = self.zero_plan(params, param_rules)
        out = {}
        for k, v in opt_state.items():
            if k in (self._ZERO_RES, self._SENT):
                continue
            if k not in plan:
                out[k] = v
                continue
            shape, size, n, chunk = plan[k]
            out[k] = jax.tree_util.tree_map(
                lambda x: _np.asarray(x).reshape(-1)[:size].reshape(shape)
                if tuple(getattr(x, "shape", ())) == (n, chunk) else x, v)
        return out

    # -- sharding ------------------------------------------------------------
    def shardings(self, params, opt_state, aux, param_rules=None):
        """Shardings for a carry whose opt_state is already in the
        layout :meth:`place` produces (zero keys as (num_shards, chunk)
        views); leaves not in that layout mirror their param."""
        mesh = self.mesh
        if mesh is None:
            return None
        rules = self.param_rules if param_rules is None else param_rules
        ps = param_shardings(params, mesh, rules)
        rep = replicated(mesh)
        plan = self.zero_plan(params, rules)
        axes = self._zero_axes()
        zspec = NamedSharding(mesh, P(axes, None)) if axes else rep

        def opt_shard(k):
            def leaf(x):
                shape = tuple(getattr(x, "shape", ()))
                if k in plan and shape == (plan[k][2], plan[k][3]):
                    return zspec
                if not shape:
                    return rep
                return ps.get(k, rep)
            return leaf

        opt_s = {}
        for k, v in opt_state.items():
            if k == self._ZERO_RES:
                opt_s[k] = jax.tree_util.tree_map(lambda _x: zspec, v)
            else:
                opt_s[k] = jax.tree_util.tree_map(opt_shard(k), v)
        aux_s = {k: rep for k in aux}
        return ps, opt_s, aux_s

    # -- compile -------------------------------------------------------------
    def _loss_closure(self):
        """The (params, aux, batch, key) -> (loss, (outs, aux_updates))
        closure with the remat mode applied — shared between
        :meth:`_build` and :meth:`residual_stats` so the measured
        residual set is exactly the compiled step's."""
        graph = self._graph
        loss_fn = self.loss_fn
        data_names, label_names = self.data_names, self.label_names
        cdtype = self.compute_dtype

        def loss_of(params_c, aux_c, batch, key):
            values = {}
            values.update(params_c)
            values.update(aux_c)
            for n in data_names + label_names:
                values[n] = batch[n]
            if cdtype is not None:
                for n in data_names:
                    values[n] = values[n].astype(cdtype)
            outs, aux_updates = graph(values, key)
            label = batch[label_names[0]] if label_names else None
            loss = loss_fn(outs[0].astype(jnp.float32), label)
            return loss, (outs, aux_updates)

        if self.remat:
            # remat=True: full recompute (the reference's
            # MXNET_BACKWARD_DO_MIRROR). remat="conv": save outputs of the
            # MXU-bound primitives (_SAVEABLE_PRIMS — convs, matmuls AND
            # the custom_vjp/pallas prims the fused units trace as) and
            # recompute the cheap elementwise tail (BN apply, ReLU, pad)
            # inside backward — on a bandwidth-bound graph this trades
            # spare MXU FLOPs for HBM traffic (see PROFILE.md).
            # remat="pass": the per-SITE IR plan (ir/remat.py) — saved
            # node outputs carry checkpoint_name tags from the graph
            # closure and the policy keeps exactly those names.
            if self.remat == "pass":
                from ..ir.remat import policy_for

                loss_of = jax.checkpoint(
                    loss_of, policy=policy_for(self._remat_plan))
            elif self.remat == "conv":
                def _policy(prim, *_, **__):
                    return prim.name in _SAVEABLE_PRIMS

                loss_of = jax.checkpoint(loss_of, policy=_policy)
            else:
                loss_of = jax.checkpoint(loss_of, static_argnums=())
        return loss_of

    def residual_stats(self, params, aux, batch, key=None):
        """AD-level backward-residual accounting for the loss under the
        current remat mode (``jax.ad_checkpoint.saved_residuals``):
        ``residual_bytes`` is the total the backward pass must hold,
        ``n_residuals`` the entry count. This is the remat decision's
        direct, backend-independent measure — XLA's CPU pipeline strips
        optimization barriers and CSE-merges the recompute back into
        the forward, so ``compiled_memory_stats`` on CPU cannot see
        what the TPU compiler (which honors the barriers) does; the
        residual set is what the policy actually changed."""
        try:
            from jax.ad_checkpoint import saved_residuals
        except ImportError:  # not re-exported publicly on jax 0.4.x
            from jax._src.ad_checkpoint import saved_residuals

        if key is None:
            from .. import random as _rnd

            key = _rnd.next_key()
        loss_of = self._loss_closure()
        res = saved_residuals(
            lambda p: loss_of(p, aux, batch, key), params)
        total = 0
        for aval, _src in res:
            n = 1
            for d in aval.shape:
                n *= int(d)
            total += n * aval.dtype.itemsize
        return {"residual_bytes": int(total), "n_residuals": len(res)}

    def _build(self, params, opt_state, aux, param_rules=None):
        opt = self.optimizer
        data_names, label_names = self.data_names, self.label_names
        aux_names = list(self.aux_names)
        loss_of = self._loss_closure()
        cdtype = self.compute_dtype

        normalize = self.normalize_grads
        want_stats = self.metric_stats

        # -- ZeRO weight-update sharding (ISSUE 7 tentpole) ------------------
        rules = self.param_rules if param_rules is None else param_rules
        plan = self.zero_plan(params, rules)
        mesh = self.mesh
        zaxes = self._zero_axes()
        zspec = NamedSharding(mesh, P(zaxes, None)) if plan else None
        zrep = replicated(mesh) if plan else None
        wire2bit = bool(plan) and self.zero_wire == "2bit"
        zthresh = self.zero_threshold
        zres_key = self._ZERO_RES

        def zsplit(x, n, chunk, size):
            flat = x.reshape(-1)
            pad = n * chunk - size
            if pad:
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad,), flat.dtype)])
            return flat.reshape(n, chunk)

        def apply_update(params_c, grads, opt_state_c, step_no):
            """Optimizer update; with a zero plan, the explicit
            reduce-scatter → 1/N-shard update → all-gather. The update
            math is elementwise per key (sgd/momentum/adam/wd/lr_mult),
            so running it on the padded flat view is bit-identical to
            the replicated update on the original shape."""
            if not plan:
                return opt.apply(params_c, grads, opt_state_c, step_no)
            wsc = jax.lax.with_sharding_constraint
            res = opt_state_c.get(zres_key)
            core = {k: v for k, v in opt_state_c.items() if k != zres_key}
            vp, vg, new_res = {}, {}, {}
            for k, w in params_c.items():
                if k not in plan:
                    vp[k] = w
                    vg[k] = grads[k]
                    continue
                _shape, size, n, chunk = plan[k]
                # THE reduce-scatter point: constraining the gradient's
                # (shards, chunk) view to the data axes lets XLA emit a
                # reduce-scatter — each device materializes only its
                # 1/N shard of the gradient sum (arXiv:2004.13336)
                g = wsc(zsplit(grads[k], n, chunk, size), zspec)
                if wire2bit:
                    # PR 4 two-bit quantizer on the reduce-scattered
                    # wire: error-feedback residual is 1/N-sharded too
                    from ..kvstore import two_bit_round_trip_core

                    g, r = two_bit_round_trip_core(
                        g.astype(jnp.float32), res[k], zthresh)
                    new_res[k] = wsc(r, zspec)
                    g = wsc(g, zspec)
                vg[k] = g
                # the replicated param's shard view is a local slice
                vp[k] = wsc(zsplit(w, n, chunk, size), zspec)
            new_p, new_s = opt.apply(vp, vg, core, step_no)
            out_p = {}
            for k, w in new_p.items():
                if k not in plan:
                    out_p[k] = w
                    continue
                shape, size, _n, _chunk = plan[k]
                # THE all-gather point: the updated 1/N shards rebuild
                # the replicated weights for the next forward
                out_p[k] = wsc(w, zrep).reshape(-1)[:size].reshape(shape)
            if wire2bit:
                new_s = dict(new_s)
                new_s[zres_key] = new_res
            return out_p, new_s

        def metric_stats_of(loss, outs, batch):
            """Reducible per-batch metric statistics, computed on the
            sharded global arrays inside the program (cross-shard sums
            compile to the same psum tree as the loss). Counts are int32
            (exact for any epoch < 2^31 rows); sums are float32."""
            out0 = outs[0]
            n_rows = out0.shape[0]
            stats = {
                "n": jnp.asarray(n_rows, jnp.int32),
                "sum_loss": loss.astype(jnp.float32) * n_rows,
            }
            if label_names and label_names[0] in batch:
                label = batch[label_names[0]]
                if (out0.ndim == 2 and label.ndim == 1
                        and label.shape[0] == out0.shape[0]):
                    lbl = label.astype(jnp.int32)
                    probs = out0.astype(jnp.float32)
                    pred = jnp.argmax(probs, axis=-1).astype(jnp.int32)
                    stats["correct"] = jnp.sum(
                        (pred == lbl).astype(jnp.int32))
                    picked = jnp.take_along_axis(
                        probs, lbl[:, None], axis=-1)[:, 0]
                    stats["sum_ce"] = -jnp.sum(jnp.log(picked + 1e-12))
            return stats

        sentinel = self.sentinel
        sent_key = self._SENT

        def health_word(loss, grads, new_params):
            """(healthy, finite_loss, finite_grad, params_ok) — all
            replicated scalars. The grads are the mesh-global psum'd
            sums and params are replicated, so every device (and every
            host in a multi-process mesh) computes the identical word;
            no extra collective is needed beyond the psum the gradients
            already paid for."""
            finite_loss = jnp.isfinite(loss.astype(jnp.float32))
            gsq = jnp.float32(0.0)
            for g in grads.values():
                gsq = gsq + jnp.sum(jnp.square(g.astype(jnp.float32)))
            finite_grad = jnp.isfinite(gsq)
            params_ok = jnp.bool_(True)
            for v in new_params.values():
                if jnp.issubdtype(v.dtype, jnp.floating):
                    params_ok = jnp.logical_and(
                        params_ok, jnp.all(jnp.isfinite(v)))
            healthy = jnp.logical_and(
                jnp.logical_and(finite_loss, finite_grad), params_ok)
            return healthy, finite_loss, finite_grad, params_ok

        def step(carry, batch, key):
            params_c, opt_state_c, aux_c, step_no = carry
            if cdtype is not None:
                cast_params = {k: v.astype(cdtype) for k, v in params_c.items()}
            else:
                cast_params = params_c
            (loss, (outs, aux_updates)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(cast_params, aux_c, batch, key)
            if normalize:
                # Module convention: rescale_grad = 1/global_batch (model.py)
                bsz = batch[data_names[0]].shape[0]
                grads = {k: g / bsz for k, g in grads.items()}
            sent = opt_state_c.get(sent_key) if sentinel != "off" else None
            core_opt = opt_state_c if sent is None else \
                {k: v for k, v in opt_state_c.items() if k != sent_key}
            new_params, new_opt = apply_update(params_c, grads,
                                               core_opt, step_no)
            new_aux = dict(aux_c)
            for k, v in aux_updates.items():
                if k in new_aux:
                    new_aux[k] = v.astype(new_aux[k].dtype)
            next_step = step_no + 1
            if sent is not None:
                healthy, f_loss, f_grad, p_ok = health_word(
                    loss, grads, new_params)
                h = healthy.astype(jnp.int32)
                skipped_inc = jnp.int32(0)
                if sentinel == "skip":
                    # unhealthy step becomes a NO-OP: pre-update
                    # params/opt-state/aux selected back (bit-identical
                    # params), the step counter does not advance, and
                    # the skip is counted
                    pick = lambda new, old: jax.tree_util.tree_map(  # noqa: E731
                        lambda n, o: jnp.where(healthy, n, o), new, old)
                    new_params = pick(new_params, params_c)
                    new_opt = pick(new_opt, core_opt)
                    new_aux = pick(new_aux, aux_c)
                    next_step = step_no + h
                    skipped_inc = 1 - h
                one = jnp.int32(1)
                new_opt = dict(new_opt)
                new_opt[sent_key] = {
                    "healthy": sent["healthy"] + h,
                    "unhealthy": sent["unhealthy"] + (one - h),
                    "skipped": sent["skipped"] + skipped_inc,
                    # consecutive-unhealthy run length: resets on a
                    # healthy step (the guard's rollback trigger)
                    "consec": (sent["consec"] + (one - h)) * (one - h),
                    "nonfinite_loss": sent["nonfinite_loss"]
                    + (one - f_loss.astype(jnp.int32)),
                    "nonfinite_grad": sent["nonfinite_grad"]
                    + (one - f_grad.astype(jnp.int32)),
                    "nonfinite_param": sent["nonfinite_param"]
                    + (one - p_ok.astype(jnp.int32)),
                    "last_healthy": h,
                    "last_loss": loss.astype(jnp.float32),
                }
            new_carry = (new_params, new_opt, new_aux, next_step)
            if self.return_outputs:
                if want_stats:
                    return new_carry, (loss, tuple(outs),
                                       metric_stats_of(loss, outs, batch))
                return new_carry, (loss, tuple(outs))
            return new_carry, loss

        if mesh is None:
            self._jit_fn = jax.jit(step, donate_argnums=(0,))
            return self._bind_fused_scope(self._jit_fn)

        # in_shardings reflect the carry layout place() produces: make
        # sure a logical-layout opt_state handed to a raw compile() call
        # yields the same tree (idempotent for the placed carry)
        opt_state = self._opt_state_to_zero(opt_state, plan)
        opt_state = self._ensure_sentinel(opt_state)
        ps, opt_s, aux_s = self.shardings(params, opt_state, aux, param_rules)
        rep = replicated(mesh)
        batch_s = {
            n: data_sharding(mesh, self.data_axes)
            for n in self.data_names + self.label_names
        }
        carry_s = (ps, opt_s, aux_s, rep)
        if self.return_outputs:
            n_out = len(self.symbol.list_outputs())
            out_sh = tuple(data_sharding(mesh, self.data_axes) for _ in range(n_out))
            # `rep` as a pytree PREFIX covers the whole stats dict
            out_s = (carry_s, (rep, out_sh, rep) if want_stats
                     else (rep, out_sh))
        else:
            out_s = (carry_s, rep)
        self._jit_fn = jax.jit(
            step,
            in_shardings=(carry_s, batch_s, rep),
            out_shardings=out_s,
            donate_argnums=(0,),
        )
        return self._bind_fused_scope(self._jit_fn)

    def compile(self, params, opt_state, aux, param_rules=None):
        if param_rules is not None:
            self.param_rules = list(param_rules)
            self._step_fn = None
        if self._step_fn is None:
            self._step_fn = self._build(params, opt_state, aux, self.param_rules)
        return self._step_fn

    def compiled_memory_stats(self, carry, batch, key=None):
        """COMPILED-step memory/cost footprint from XLA's own analyses
        (ISSUE 19) — distinct from :meth:`memory_stats`, which measures
        the resident carry: ``temp_bytes`` is the compiler's peak
        scratch (activations + workspace — the number selective remat
        exists to cut), ``peak_bytes`` adds the non-aliased I/O the
        program holds live. ``flops``/``bytes_accessed`` come from
        ``cost_analysis`` and feed the pipeline ranker's features."""
        if key is None:
            from .. import random as _rnd

            key = _rnd.next_key()
        self.compile(*carry[:3])
        lower = self._jit_fn.lower
        if self.mesh is not None:
            axes = tuple(a for a in self.data_axes
                         if a in self.mesh.axis_names)
            if axes:
                from ..kernels import fused_block as _fb

                with _fb.spmd_scope(self.mesh, axes):
                    compiled = lower(carry, batch, key).compile()
            else:
                compiled = lower(carry, batch, key).compile()
        else:
            compiled = lower(carry, batch, key).compile()
        mem = compiled.memory_analysis()
        temp = int(getattr(mem, "temp_size_in_bytes", 0))
        arg = int(getattr(mem, "argument_size_in_bytes", 0))
        out = int(getattr(mem, "output_size_in_bytes", 0))
        alias = int(getattr(mem, "alias_size_in_bytes", 0))
        stats = {
            "temp_bytes": temp,
            "argument_bytes": arg,
            "output_bytes": out,
            "alias_bytes": alias,
            "peak_bytes": temp + arg + out - alias,
        }
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if isinstance(cost, dict):
            if cost.get("flops") is not None:
                stats["flops"] = float(cost["flops"])
            if cost.get("bytes accessed") is not None:
                stats["bytes_accessed"] = float(cost["bytes accessed"])
        return stats

    def place(self, params, opt_state, aux, param_rules=None):
        """device_put the carry with its shardings (host → HBM once).
        With ``zero``, optimizer state is laid out as its padded
        (num_shards, chunk) views first — accepts both the logical
        layout (init/checkpoint restore: this is where a checkpoint
        saved on a different mesh size re-splits) and an already-placed
        zero-layout carry (idempotent)."""
        if param_rules is not None:
            self.param_rules = list(param_rules)
            self._step_fn = None
        step_no = jnp.zeros((), jnp.int32)
        if self.mesh is None:
            carry = (params, self._ensure_sentinel(opt_state), aux, step_no)
            self.record_memory_stats(carry)
            return carry
        opt_state = self._opt_state_to_zero(
            opt_state, self.zero_plan(params, self.param_rules))
        opt_state = self._ensure_sentinel(opt_state)
        ps, opt_s, aux_s = self.shardings(params, opt_state, aux, self.param_rules)
        params = {k: jax.device_put(v, ps[k]) for k, v in params.items()}
        opt_state = (
            {k: jax.tree_util.tree_map(lambda x, s: jax.device_put(x, s), v, opt_s[k])
             for k, v in opt_state.items()}
        )
        aux = {k: jax.device_put(v, aux_s[k]) for k, v in aux.items()}
        step_no = jax.device_put(step_no, replicated(self.mesh))
        carry = (params, opt_state, aux, step_no)
        self.record_memory_stats(carry)
        return carry

    # -- memory observability (ISSUE 7) --------------------------------------
    def memory_stats(self, carry):
        """Measured per-device bytes of the resident carry plus analytic
        per-step estimates. ``param/opt/aux_bytes_per_dev`` are MEASURED
        (summed over this process's first mesh device's actual shards);
        ``grad_bytes_per_dev_est`` is the gradient working set the
        update consumes (1/N shards for zero-planned params) and
        ``collective_bytes_per_step_est`` the per-device wire volume of
        the gradient sync (ring all-reduce == reduce-scatter +
        all-gather: 2·size·(N-1)/N either way — ZeRO changes memory,
        not collective volume)."""
        params, opt_state, aux, _step = carry
        dev = None
        if self.mesh is not None:
            pidx = jax.process_index()
            dev = next((d for d in self.mesh.devices.flat
                        if d.process_index == pidx), None)

        def per_dev(tree):
            total = 0
            for leaf in jax.tree_util.tree_leaves(tree):
                shards = getattr(leaf, "addressable_shards", None)
                if shards is None:
                    total += int(getattr(leaf, "nbytes", 0))
                    continue
                d = dev if dev is not None else shards[0].device
                total += sum(int(s.data.nbytes) for s in shards
                             if s.device == d)
            return total

        plan = self.zero_plan(params, self.param_rules)
        grad_est = 0
        coll_est = 0
        n_total = 1
        if self.mesh is not None:
            sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
            for a in self.data_axes:
                n_total *= sizes.get(a, 1)
        for k, v in params.items():
            nbytes = int(_np.prod(tuple(v.shape) or (1,))) * \
                _np.dtype(v.dtype).itemsize
            if k in plan:
                _shape, _size, n, chunk = plan[k]
                grad_est += chunk * _np.dtype(v.dtype).itemsize
            else:
                grad_est += nbytes
            if n_total > 1:
                coll_est += int(2 * nbytes * (n_total - 1) / n_total)
        return {
            "param_bytes_per_dev": per_dev(params),
            "opt_bytes_per_dev": per_dev(opt_state),
            "aux_bytes_per_dev": per_dev(aux),
            "grad_bytes_per_dev_est": int(grad_est),
            "collective_bytes_per_step_est": coll_est,
            "zero": bool(plan),
            "zero_params": len(plan),
            "num_shards": n_total,
        }

    def record_memory_stats(self, carry):
        """Publish :meth:`memory_stats` to the profiler gauge (rides
        ``dump_profile`` as ``memoryStats``)."""
        from .. import profiler

        profiler.memory_record(**self.memory_stats(carry))

    # -- sentinel (ISSUE 9) --------------------------------------------------
    def health_stats(self, carry):
        """Drain the sentinel's device counters from a carry: one
        blocking device read of the replicated scalars (legal on every
        tier — fully-replicated arrays read their local shard). None
        when the sentinel is off."""
        sent = carry[1].get(self._SENT)
        if sent is None:
            return None

        def fetch(x):
            if getattr(x, "is_fully_addressable", True):
                return jax.device_get(x)
            return _np.asarray(x.addressable_data(0))

        vals = {k: fetch(v) for k, v in sent.items()}
        return {k: (float(v) if k == "last_loss" else int(v))
                for k, v in vals.items()}

    def _halt_check(self, new_carry):
        """halt mode: read the health word after every step (the one
        per-batch host sync, recorded honestly) and raise on the first
        unhealthy step."""
        from .. import profiler

        profiler.h2d_record(host_syncs=1)
        snap = self.health_stats(new_carry)
        if snap and not snap["last_healthy"]:
            profiler.health_sentinel(snap)
            raise MXNetError(
                "sentinel halt: unhealthy training step detected "
                "(nonfinite_loss=%d nonfinite_grad=%d nonfinite_param=%d "
                "unhealthy=%d of %d steps, last_loss=%r)"
                % (snap["nonfinite_loss"], snap["nonfinite_grad"],
                   snap["nonfinite_param"], snap["unhealthy"],
                   snap["healthy"] + snap["unhealthy"],
                   snap["last_loss"]))

    def __call__(self, carry, batch, key=None):
        if key is None:
            from .. import random as _rnd

            key = _rnd.next_key()
        fn = self.compile(*carry[:3])
        result = fn(carry, batch, key)
        if self.sentinel == "halt":
            self._halt_check(result[0])
        return result

    def _bind_fused_scope(self, fn):
        """Bind the trace-time SPMD scope for Pallas-fused ops to the
        compiled step: on a mesh, the FusedBottleneckUnit op shard_maps
        its kernels over the data axes (Mosaic kernels are opaque to
        pjit's partitioner on real TPU). The scope wraps every call of
        the returned fn — tracing is lazy, so it must be active at the
        first invocation no matter whether the caller went through
        __call__ or a raw compile()."""
        if self.mesh is None:
            return fn
        axes = tuple(a for a in self.data_axes if a in self.mesh.axis_names)
        if not axes:
            return fn
        from ..kernels import fused_block as _fb

        mesh = self.mesh

        @functools.wraps(fn)
        def scoped(*args, **kwargs):
            with _fb.spmd_scope(mesh, axes):
                return fn(*args, **kwargs)

        return scoped
