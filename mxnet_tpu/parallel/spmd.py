"""SPMD fused training step: loss + grad + optimizer update in ONE XLA program.

Reference counterpart: the hot path assembled from
``DataParallelExecutorGroup`` (python/mxnet/module/executor_group.py:128 —
batch split across devices), ``Comm::Reduce``/KVStore push-pull gradient
sync (src/kvstore/comm.h:56, kvstore_local.h), and the ``sgd_mom_update``
CUDA kernels (src/operator/optimizer_op.cc:39-286). TPU-native design: all
three stages fuse into a single ``jax.jit`` program over a
``jax.sharding.Mesh`` —

- batch arrays are sharded over the data axes (``dp``); XLA inserts the
  gradient all-reduce (psum over ICI) where the reference ran NCCL/ps-lite,
  and overlaps it with backprop via its latency-hiding scheduler (the
  reference's priority-queue overlap, model.py:126-137).
- parameters may be sharded over ``tp`` (tensor parallel) by regex rules —
  the generalization of the reference's `group2ctx` model parallelism.
- the optimizer update runs on the sharded gradients in the same program
  (no separate push/pull round trip); with weight-update sharding
  (`zero=True`) each dp-shard updates a slice of the weights and
  all-gathers — the ZeRO analogue of the reference's server-side optimizer
  (kvstore_dist_server.h set_optimizer).
- mixed precision: master weights fp32, compute in ``compute_dtype``
  (bfloat16 on the MXU) — the mp_sgd_* multi-precision pattern
  (src/operator/optimizer_op.cc mp_sgd_update) without a separate kernel.

This module is pure-functional (params/states are pytrees, not NDArrays):
it is the engine under ``kvstore='tpu'`` Module training, ``bench.py`` and
``__graft_entry__.py``.
"""
from __future__ import annotations

import contextlib
import functools
import re

import jax
import jax.numpy as jnp
import numpy as _np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = [
    "param_shardings", "data_sharding", "replicated", "make_train_step",
    "TrainStep", "functional_optimizer", "functional_from_optimizer",
    "cross_entropy_loss",
]


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------
def replicated(mesh):
    return NamedSharding(mesh, P())


def data_sharding(mesh, axes=("dp",), ndim=None):
    """Shard the leading (batch) dimension over the given mesh axes."""
    names = [a for a in axes if a in mesh.axis_names]
    spec = P(tuple(names)) if names else P()
    return NamedSharding(mesh, spec)


def param_shardings(params, mesh, rules=None):
    """Map param name -> NamedSharding via ordered (regex, PartitionSpec)
    rules; first match wins, default replicated.

    Example rules for megatron-style tensor parallelism::

        [(r".*ffn_up_weight",  P("tp", None)),   # (out, in): shard out dim
         (r".*ffn_down_weight", P(None, "tp")),
         (r".*", P())]
    """
    rules = rules or []
    out = {}
    for name, v in params.items():
        spec = P()
        for pat, s in rules:
            if re.match(pat, name):
                spec = s if isinstance(s, P) else P(*s)
                break
        if spec != P() and not _spec_fits(spec, v.shape, mesh):
            spec = P()  # unknown axis or indivisible dim: replicate
        out[name] = NamedSharding(mesh, spec)
    return out


def _spec_fits(spec, shape, mesh):
    """True iff every axis in spec exists on the mesh and divides its dim."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    for dim, ax in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if ax is None:
            continue
        axs = (ax,) if isinstance(ax, str) else tuple(ax)
        n = 1
        for a in axs:
            if a not in sizes:
                return False
            n *= sizes[a]
        if dim % n != 0:
            return False
    return True


# ---------------------------------------------------------------------------
# functional optimizers (pure mirrors of optimizer.py classes, built on the
# registered pure-JAX update ops in ops/optimizer_ops.py)
# ---------------------------------------------------------------------------
class FunctionalOptimizer:
    """init(params)->state pytree; apply(params, grads, state, step)->new."""

    def __init__(self, init, apply, hyper=None):
        self.init = init
        self.apply = apply
        self.hyper = dict(hyper or {})


def functional_optimizer(name="sgd", learning_rate=0.01, momentum=0.0, wd=0.0,
                         beta1=0.9, beta2=0.999, epsilon=1e-8,
                         rescale_grad=1.0, clip_gradient=None,
                         lr_scheduler=None, wd_pattern=r".*(weight|gamma)$",
                         lr_mult=None, wd_mult=None):
    """Build a pure optimizer. ``wd_pattern``: params matching get weight
    decay, others (bias/beta/moving stats) get 0 — set_wd_mult parity
    (python/mxnet/optimizer.py set_wd_mult). Explicit per-name ``lr_mult``
    / ``wd_mult`` dicts (default multiplier 1.0) override the pattern,
    mirroring Optimizer.set_lr_mult/set_wd_mult exactly."""
    name = name.lower()
    wd_re = re.compile(wd_pattern)

    def lr_at(step):
        if lr_scheduler is not None:
            return lr_scheduler(step)
        return learning_rate

    def mults(k):
        lm = 1.0 if lr_mult is None else float(lr_mult.get(k, 1.0))
        if wd_mult is not None:
            wm = wd * float(wd_mult.get(k, 1.0))
        else:
            wm = wd if wd_re.match(k) else 0.0
        return lm, wm

    def preprocess(g):
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient is not None and clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        return g

    if name == "sgd":
        def init(params):
            if momentum == 0.0:
                return {}
            return {k: jnp.zeros_like(v) for k, v in params.items()}

        def apply(params, grads, state, step):
            lr = lr_at(step)
            new_p, new_s = {}, {}
            for k, w in params.items():
                g = preprocess(grads[k])
                lm, this_wd = mults(k)
                g = g + this_wd * w
                if momentum == 0.0:
                    new_p[k] = w - (lr * lm) * g
                else:
                    m = momentum * state[k] - (lr * lm) * g
                    new_s[k] = m
                    new_p[k] = w + m
            return new_p, new_s

        return FunctionalOptimizer(init, apply, dict(lr=learning_rate, momentum=momentum, wd=wd))

    if name == "adam":
        def init(params):
            return {
                k: (jnp.zeros_like(v), jnp.zeros_like(v)) for k, v in params.items()
            }

        def apply(params, grads, state, step):
            lr = lr_at(step)
            t = step.astype(jnp.float32) + 1.0
            coef1 = 1.0 - beta1 ** t
            coef2 = 1.0 - beta2 ** t
            lr_t = lr * jnp.sqrt(coef2) / coef1
            new_p, new_s = {}, {}
            for k, w in params.items():
                g = preprocess(grads[k])
                lm, this_wd = mults(k)
                g = g + this_wd * w
                m, v = state[k]
                m = beta1 * m + (1 - beta1) * g
                v = beta2 * v + (1 - beta2) * g * g
                new_s[k] = (m, v)
                new_p[k] = w - (lr_t * lm) * m / (jnp.sqrt(v) + epsilon)
            return new_p, new_s

        return FunctionalOptimizer(init, apply, dict(lr=learning_rate, wd=wd))

    raise MXNetError("functional_optimizer: unknown optimizer %r" % name)


def functional_from_optimizer(opt, param_names):
    """Map an imperative ``optimizer.Optimizer`` instance to the pure
    FunctionalOptimizer used by the fused SPMD step (Module kvstore='tpu').

    Raises MXNetError for optimizers/features the fused path cannot
    reproduce exactly (callers fall back to per-executor update).
    """
    from .. import optimizer as opt_mod

    if opt.lr_scheduler is not None:
        raise MXNetError(
            "fused SPMD step: lr_scheduler uses python control flow per "
            "update and cannot be traced; falling back")
    if getattr(opt, "param_dict", None):
        raise MXNetError("fused SPMD step: param_dict mults not supported")
    lr_mult = {n: opt.lr_mult.get(n, 1.0) for n in param_names}
    wd_mult = {n: opt.wd_mult.get(n, 1.0) for n in param_names}
    common = dict(
        learning_rate=opt.lr, wd=opt.wd, rescale_grad=opt.rescale_grad,
        clip_gradient=opt.clip_gradient, lr_mult=lr_mult, wd_mult=wd_mult,
    )
    if type(opt) is opt_mod.SGD:
        return functional_optimizer("sgd", momentum=opt.momentum, **common)
    if type(opt) is opt_mod.Adam:
        return functional_optimizer(
            "adam", beta1=opt.beta1, beta2=opt.beta2, epsilon=opt.epsilon, **common)
    raise MXNetError(
        "fused SPMD step: optimizer %s has no functional mirror"
        % type(opt).__name__)


def cross_entropy_loss(probs, label, eps=1e-12):
    """Mean CE given probabilities (SoftmaxOutput forward emits probs)."""
    lbl = label.astype(jnp.int32).reshape(-1)
    p = probs.reshape(lbl.shape[0], -1)
    picked = jnp.take_along_axis(p, lbl[:, None], axis=-1)
    return -jnp.mean(jnp.log(picked + eps))


# ---------------------------------------------------------------------------
# the fused train step
# ---------------------------------------------------------------------------
class TrainStep:
    """Compiled SPMD training step for a Symbol graph.

    step(carry, batch) -> (carry, loss); carry = (params, opt_state,
    aux, step_no), all device-resident and donated between steps.

    Gradient semantics: gradients flow through the graph exactly as the
    reference's ``Executor::Backward`` with ones head-grads — fused loss
    heads (SoftmaxOutput & co.) substitute their own backward
    (sum-CE gradient), so for such graphs ``loss_fn`` only affects the
    *reported* loss, not the gradients (reference parity:
    src/operator/softmax_output.cc discards out_grad unless out_grad=True).
    ``normalize_grads=True`` (default) divides gradients by global batch
    size, mirroring Module's ``rescale_grad=1/batch`` convention so lr
    values transfer.

    ``zero=True`` shards optimizer state over the data axes (weight-update
    sharding / ZeRO: XLA reduce-scatters grads into the update and
    all-gathers the new weights — the TPU answer to the reference's
    server-side optimizer, kvstore_dist_server.h).

    ``metric_stats=True`` (requires ``return_outputs=True``) additionally
    returns a dict of replicated per-batch metric statistics computed
    INSIDE the compiled program — ``n`` (rows), ``sum_loss`` (loss·n),
    and, for a 2-D first output with a 1-D label, ``correct`` (argmax
    match count) and ``sum_ce`` (summed -log p[label], eps 1e-12,
    mirroring metric.CrossEntropy). The fit loop accumulates these on
    device so no per-batch host sync is needed to keep metrics
    (ISSUE 5 device-resident metrics). Step returns become
    ``(carry, (loss, outputs, stats))``.
    """

    def __init__(self, symbol, optimizer, mesh=None, data_axes=("dp",),
                 param_rules=None, label_names=("softmax_label",),
                 data_names=("data",), compute_dtype=None, loss_fn=None,
                 zero=False, remat=False, normalize_grads=True,
                 return_outputs=False, metric_stats=False):
        from ..executor import _graph_closure

        self.symbol = symbol
        self.mesh = mesh
        self.data_axes = tuple(data_axes)
        self.optimizer = (
            optimizer if isinstance(optimizer, FunctionalOptimizer)
            else functional_optimizer(**optimizer) if isinstance(optimizer, dict)
            else functional_optimizer(optimizer)
        )
        self.label_names = tuple(label_names)
        self.data_names = tuple(data_names)
        self.compute_dtype = compute_dtype
        self.loss_fn = loss_fn or cross_entropy_loss
        self.zero = zero
        self.remat = remat
        self.normalize_grads = normalize_grads
        self.return_outputs = return_outputs
        if metric_stats and not return_outputs:
            raise MXNetError(
                "TrainStep: metric_stats=True requires return_outputs=True")
        self.metric_stats = metric_stats
        self.param_rules = list(param_rules or [])

        arg_names = symbol.list_arguments()
        self.param_names = [
            n for n in arg_names if n not in self.data_names and n not in self.label_names
        ]
        self.aux_names = symbol.list_auxiliary_states()
        self._graph = _graph_closure(symbol, is_train=True)
        self._step_fn = None

    # -- initialization ------------------------------------------------------
    def init_params(self, data_shapes, initializer=None, dtype=_np.float32, seed=0):
        """Infer shapes from data shapes and initialize params/aux.

        All allocation happens on the target mesh's first device (or the
        process default when no mesh is set) so that a mesh built from
        non-default devices — e.g. the 8-CPU-device dryrun mesh while the
        default platform is a TPU — never touches the default device.
        """
        from ..initializer import Uniform, InitDesc

        shape_kwargs = dict(data_shapes)
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**shape_kwargs)
        arg_names = self.symbol.list_arguments()
        init = initializer or Uniform(0.01)
        params, aux = {}, {}
        dev = None
        if self.mesh is not None:
            # First *addressable* device: in a multi-host mesh, devices.flat[0]
            # may belong to another process and cannot host allocations.
            pidx = jax.process_index()
            dev = next((d for d in self.mesh.devices.flat if d.process_index == pidx), None)
        ctx = jax.default_device(dev) if dev is not None else contextlib.nullcontext()
        np_state = _np.random.get_state()
        _np.random.seed(seed)
        # the initializer zoo draws from the module-owned RNG
        # (random.initializer_rng), not the global numpy one — seed it
        # too, else same-seed init_params differs across processes
        from .. import random as _rnd_mod

        prev_init_rng = _rnd_mod._INIT_RNG
        _rnd_mod._INIT_RNG = _np.random.RandomState(int(seed) & 0x7FFFFFFF)
        try:
            with ctx:
                for name, shape in zip(arg_names, arg_shapes):
                    if name in self.data_names or name in self.label_names:
                        continue
                    from ..ndarray.ndarray import zeros as nd_zeros

                    arr = nd_zeros(shape, dtype=dtype)
                    init(InitDesc(name), arr)
                    params[name] = arr._data()
                for name, shape in zip(self.aux_names, aux_shapes):
                    val = jnp.ones(shape, dtype) if "var" in name or "gamma" in name else jnp.zeros(shape, dtype)
                    aux[name] = val
                opt_state = self.optimizer.init(params)
        finally:
            _np.random.set_state(np_state)
            _rnd_mod._INIT_RNG = prev_init_rng
        return params, opt_state, aux

    # -- sharding ------------------------------------------------------------
    def shardings(self, params, opt_state, aux, param_rules=None):
        mesh = self.mesh
        if mesh is None:
            return None
        rules = self.param_rules if param_rules is None else param_rules
        ps = param_shardings(params, mesh, rules)
        rep = replicated(mesh)
        if self.zero:
            # ZeRO / weight-update sharding: optimizer state shards its
            # leading dim over the data axes (stacked with any tp sharding
            # the param already has on later dims).
            def zero_shard(k):
                def leaf(x):
                    if x.ndim == 0:
                        return rep
                    base = list(tuple(ps[k].spec) + (None,) * (x.ndim - len(ps[k].spec)))
                    if base[0] is not None:  # already tp-sharded on dim 0
                        return ps[k]
                    spec = P(*([self.data_axes] + base[1:]))
                    if _spec_fits(spec, x.shape, mesh):
                        return NamedSharding(mesh, spec)
                    return ps[k]
                return leaf

            opt_s = {k: jax.tree_util.tree_map(zero_shard(k), v)
                     for k, v in opt_state.items()}
        else:
            # opt state mirrors its param's sharding
            opt_s = {k: jax.tree_util.tree_map(lambda _, k=k: ps[k], v)
                     for k, v in opt_state.items()}
        aux_s = {k: rep for k in aux}
        return ps, opt_s, aux_s

    # -- compile -------------------------------------------------------------
    def _build(self, params, opt_state, aux, param_rules=None):
        graph = self._graph
        opt = self.optimizer
        loss_fn = self.loss_fn
        data_names, label_names = self.data_names, self.label_names
        aux_names = list(self.aux_names)
        cdtype = self.compute_dtype

        def loss_of(params_c, aux_c, batch, key):
            values = {}
            values.update(params_c)
            values.update(aux_c)
            for n in data_names + label_names:
                values[n] = batch[n]
            if cdtype is not None:
                for n in data_names:
                    values[n] = values[n].astype(cdtype)
            outs, aux_updates = graph(values, key)
            label = batch[label_names[0]] if label_names else None
            loss = loss_fn(outs[0].astype(jnp.float32), label)
            return loss, (outs, aux_updates)

        if self.remat:
            # remat=True: full recompute (the reference's
            # MXNET_BACKWARD_DO_MIRROR). remat="conv": save only conv/dot
            # outputs and recompute the cheap elementwise tail (BN apply,
            # ReLU, pad) inside backward — on a bandwidth-bound graph this
            # trades spare MXU FLOPs for HBM traffic (see PROFILE.md).
            if self.remat == "conv":
                def _policy(prim, *_, **__):
                    return prim.name in ("conv_general_dilated", "dot_general")

                loss_of = jax.checkpoint(loss_of, policy=_policy)
            else:
                loss_of = jax.checkpoint(loss_of, static_argnums=())

        normalize = self.normalize_grads
        want_stats = self.metric_stats

        def metric_stats_of(loss, outs, batch):
            """Reducible per-batch metric statistics, computed on the
            sharded global arrays inside the program (cross-shard sums
            compile to the same psum tree as the loss). Counts are int32
            (exact for any epoch < 2^31 rows); sums are float32."""
            out0 = outs[0]
            n_rows = out0.shape[0]
            stats = {
                "n": jnp.asarray(n_rows, jnp.int32),
                "sum_loss": loss.astype(jnp.float32) * n_rows,
            }
            if label_names and label_names[0] in batch:
                label = batch[label_names[0]]
                if (out0.ndim == 2 and label.ndim == 1
                        and label.shape[0] == out0.shape[0]):
                    lbl = label.astype(jnp.int32)
                    probs = out0.astype(jnp.float32)
                    pred = jnp.argmax(probs, axis=-1).astype(jnp.int32)
                    stats["correct"] = jnp.sum(
                        (pred == lbl).astype(jnp.int32))
                    picked = jnp.take_along_axis(
                        probs, lbl[:, None], axis=-1)[:, 0]
                    stats["sum_ce"] = -jnp.sum(jnp.log(picked + 1e-12))
            return stats

        def step(carry, batch, key):
            params_c, opt_state_c, aux_c, step_no = carry
            if cdtype is not None:
                cast_params = {k: v.astype(cdtype) for k, v in params_c.items()}
            else:
                cast_params = params_c
            (loss, (outs, aux_updates)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(cast_params, aux_c, batch, key)
            if normalize:
                # Module convention: rescale_grad = 1/global_batch (model.py)
                bsz = batch[data_names[0]].shape[0]
                grads = {k: g / bsz for k, g in grads.items()}
            new_params, new_opt = opt.apply(params_c, grads, opt_state_c, step_no)
            new_aux = dict(aux_c)
            for k, v in aux_updates.items():
                if k in new_aux:
                    new_aux[k] = v.astype(new_aux[k].dtype)
            new_carry = (new_params, new_opt, new_aux, step_no + 1)
            if self.return_outputs:
                if want_stats:
                    return new_carry, (loss, tuple(outs),
                                       metric_stats_of(loss, outs, batch))
                return new_carry, (loss, tuple(outs))
            return new_carry, loss

        mesh = self.mesh
        if mesh is None:
            return self._bind_fused_scope(jax.jit(step, donate_argnums=(0,)))

        ps, opt_s, aux_s = self.shardings(params, opt_state, aux, param_rules)
        rep = replicated(mesh)
        batch_s = {
            n: data_sharding(mesh, self.data_axes)
            for n in self.data_names + self.label_names
        }
        carry_s = (ps, opt_s, aux_s, rep)
        if self.return_outputs:
            n_out = len(self.symbol.list_outputs())
            out_sh = tuple(data_sharding(mesh, self.data_axes) for _ in range(n_out))
            # `rep` as a pytree PREFIX covers the whole stats dict
            out_s = (carry_s, (rep, out_sh, rep) if want_stats
                     else (rep, out_sh))
        else:
            out_s = (carry_s, rep)
        return self._bind_fused_scope(jax.jit(
            step,
            in_shardings=(carry_s, batch_s, rep),
            out_shardings=out_s,
            donate_argnums=(0,),
        ))

    def compile(self, params, opt_state, aux, param_rules=None):
        if param_rules is not None:
            self.param_rules = list(param_rules)
            self._step_fn = None
        if self._step_fn is None:
            self._step_fn = self._build(params, opt_state, aux, self.param_rules)
        return self._step_fn

    def place(self, params, opt_state, aux, param_rules=None):
        """device_put the carry with its shardings (host → HBM once)."""
        if param_rules is not None:
            self.param_rules = list(param_rules)
            self._step_fn = None
        step_no = jnp.zeros((), jnp.int32)
        if self.mesh is None:
            return (params, opt_state, aux, step_no)
        ps, opt_s, aux_s = self.shardings(params, opt_state, aux, self.param_rules)
        params = {k: jax.device_put(v, ps[k]) for k, v in params.items()}
        opt_state = (
            {k: jax.tree_util.tree_map(lambda x, s: jax.device_put(x, s), v, opt_s[k])
             for k, v in opt_state.items()}
        )
        aux = {k: jax.device_put(v, aux_s[k]) for k, v in aux.items()}
        step_no = jax.device_put(step_no, replicated(self.mesh))
        return (params, opt_state, aux, step_no)

    def __call__(self, carry, batch, key=None):
        if key is None:
            from .. import random as _rnd

            key = _rnd.next_key()
        fn = self.compile(*carry[:3])
        return fn(carry, batch, key)

    def _bind_fused_scope(self, fn):
        """Bind the trace-time SPMD scope for Pallas-fused ops to the
        compiled step: on a mesh, the FusedBottleneckUnit op shard_maps
        its kernels over the data axes (Mosaic kernels are opaque to
        pjit's partitioner on real TPU). The scope wraps every call of
        the returned fn — tracing is lazy, so it must be active at the
        first invocation no matter whether the caller went through
        __call__ or a raw compile()."""
        if self.mesh is None:
            return fn
        axes = tuple(a for a in self.data_axes if a in self.mesh.axis_names)
        if not axes:
            return fn
        from ..kernels import fused_block as _fb

        mesh = self.mesh

        @functools.wraps(fn)
        def scoped(*args, **kwargs):
            with _fb.spmd_scope(mesh, axes):
                return fn(*args, **kwargs)

        return scoped
