"""Asynchronous host→device input pipeline.

Reference counterpart: the prefetch side of ``src/io/iter_prefetcher.h``
plus the pinned-memory staging the reference's GPU path got from
``cudaMemcpyAsync``. TPU-native design: the compiled fused step consumes
batches already sharded over the mesh (``NamedSharding`` over the data
axes), so the only host work left per batch is the ``jax.device_put`` —
and that transfer is exactly what :class:`DeviceQueueIter` moves off the
hot loop. A background thread converts/shards batch N+1 while step N
computes; the consumer pops finished device batches from a bounded queue
(depth ``MXNET_TPU_FEED_DEPTH``, default 2) so host memory stays bounded
and backpressure reaches the source iterator.

The placement function (:func:`place_batch_array`) is shared with
``FusedSPMDGroup`` so the pipelined path is bit-identical to the
synchronous one — single-chip ``device_put`` and multi-process
``make_array_from_process_local_data`` both included.
"""
from __future__ import annotations

import queue
import threading
import time
import warnings

import numpy as np

from .. import profiler
from ..base import MXNetError
from ..io import DataBatch, DataIter
from ..ndarray.ndarray import NDArray


def expected_sharding(mesh, data_axes):
    """The NamedSharding a batch array carries on this mesh's data axes —
    MUST stay bit-identical to the compiled step's input sharding, so it
    delegates to the one implementation (spmd.data_sharding): any
    divergence would silently defeat the is_preplaced fast path."""
    from .spmd import data_sharding

    return data_sharding(mesh, data_axes)


def is_preplaced(value, sharding):
    """True when ``value`` is already a device array laid out exactly as
    the compiled step expects (the DeviceQueueIter fast path)."""
    vs = getattr(value, "sharding", None)
    if vs is None:
        return False
    try:
        return vs.is_equivalent_to(sharding, value.ndim)
    except (TypeError, ValueError):
        return False


def place_batch_array(mesh, data_axes, distributed, name, value,
                      sharding=None):
    """Host batch array → device: local ``device_put``, or the
    process-local shard of the global batch in distributed mode. Records
    bytes/latency into the profiler's pipeline counters. ``value`` may be
    numpy or a single-device jax array; pre-placed arrays short-circuit.
    """
    import jax

    sharding = sharding or expected_sharding(mesh, data_axes)
    if is_preplaced(value, sharding):
        profiler.h2d_record(preplaced=1)
        return value
    t0 = time.perf_counter()
    if not distributed or jax.process_count() == 1:
        ndev = mesh.devices.size
        if value.shape[0] % ndev != 0:
            raise MXNetError(
                "async feed: batch dim %d of %r not divisible by "
                "%d mesh devices" % (value.shape[0], name, ndev))
        out = jax.device_put(value, sharding)
    else:
        local = np.asarray(value)
        nproc = jax.process_count()
        if local.shape[0] % jax.local_device_count() != 0:
            raise MXNetError(
                "async feed: local batch dim %d of %r not divisible "
                "by %d local devices"
                % (local.shape[0], name, jax.local_device_count()))
        out = jax.make_array_from_process_local_data(
            sharding, local,
            global_shape=(local.shape[0] * nproc,) + local.shape[1:])
    # size*itemsize, NOT np.asarray(value).nbytes: forcing a host
    # materialization just for byte accounting would re-add the very
    # per-batch copy this path exists to remove
    nbytes = int(value.size) * np.dtype(value.dtype).itemsize
    profiler.h2d_record(nbytes=nbytes, puts=1,
                        seconds=time.perf_counter() - t0)
    return out


_END = object()    # inner iterator exhausted
_ABORT = object()  # worker thread died; see self._exc


class DeviceQueueIter(DataIter):
    """Wrap any :class:`DataIter` so batches arrive on the mesh already
    sharded, converted on a background thread while the previous step
    computes (ISSUE 5 tentpole).

    Parameters
    ----------
    data_iter : DataIter
        The host-side source iterator.
    group : FusedSPMDGroup, optional
        Take ``mesh``/``data_axes``/``distributed`` from a Module's fused
        group directly.
    module : Module, optional
        Bind lazily to ``module``'s fused group: resolution happens on
        the first ``next()``, which in ``Module.fit`` is after
        ``init_optimizer`` created the group — so the wrapper can be
        built BEFORE ``fit`` is called. When the module has no fused
        group (kvstore is not 'tpu'/'dist_*'), the iterator degrades to
        a transparent pass-through of host batches (with a warning).
    mesh, data_axes, distributed :
        Explicit placement spec when neither group nor module is given.
    depth : int
        Bounded pipeline depth (batches staged on device ahead of the
        consumer). Default ``MXNET_TPU_FEED_DEPTH`` (2).
    close_source : bool
        Whether :meth:`close` also closes ``data_iter``. Default True;
        auto-wrappers around a CALLER-owned iterator (``FeedForward.fit``)
        pass False so the caller can keep using it.

    Supports ``with DeviceQueueIter(...) as it:`` and explicit
    :meth:`close`; ``reset()`` restarts cleanly after ``StopIteration``
    or mid-epoch abandonment.
    """

    def __init__(self, data_iter, group=None, module=None, mesh=None,
                 data_axes=("dp",), distributed=False, depth=None,
                 close_source=True):
        super().__init__(getattr(data_iter, "batch_size", 0))
        from .. import config

        if depth is None:
            depth = config.get_int("MXNET_TPU_FEED_DEPTH", 2)
        depth = int(depth)
        if depth < 1:
            raise MXNetError(
                "DeviceQueueIter: depth must be >= 1 (got %d); set "
                "MXNET_TPU_FEED_DEPTH to a positive integer" % depth)
        self.data_iter = data_iter
        self.depth = depth
        self._close_source = bool(close_source)
        self._module = module
        self._passthrough = False
        self._group = None
        self.mesh = None
        self._checked_agreement = False
        self._local_rows = None   # constant-local-batch invariant (dist)
        self._closed = False
        self._thread = None
        self._q = None
        self._exc = None
        self._stop = threading.Event()
        self._current_batch = None
        if group is not None or mesh is not None:
            self._bind(group=group, mesh=mesh, data_axes=data_axes,
                       distributed=distributed)
        elif module is None:
            raise MXNetError(
                "DeviceQueueIter: need a mesh (or group=/module=)")
        # module= defers binding to the first next()

    def _bind(self, group=None, mesh=None, data_axes=("dp",),
              distributed=False):
        if group is not None:
            mesh = group.mesh
            data_axes = group._data_axes
            distributed = group.distributed
        self.mesh = mesh
        self.data_axes = tuple(data_axes)
        self.distributed = bool(distributed)
        self._sharding = expected_sharding(mesh, self.data_axes)
        self._group = group

    def _ensure_started(self):
        """Resolve deferred module binding and start the worker."""
        if self._thread is not None or self._passthrough:
            return
        if self.mesh is None:
            fused = getattr(self._module, "_fused", None)
            if fused is None:
                warnings.warn(
                    "DeviceQueueIter: module has no fused SPMD group "
                    "(kvstore != 'tpu'); passing host batches through "
                    "unchanged", stacklevel=3)
                self._passthrough = True
                return
            self._bind(group=fused)
        self._start()

    # -- pass-through metadata ----------------------------------------------
    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    # -- worker --------------------------------------------------------------
    def _start(self):
        self._q = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._exc = None
        # the worker binds THIS generation's queue/stop-event as locals:
        # a reset() that times out joining a wedged worker replaces both,
        # and the abandoned thread must never be able to inject a stale
        # pre-reset batch into the new epoch's queue
        t = threading.Thread(target=self._worker,
                             args=(self._q, self._stop),
                             name="DeviceQueueIter", daemon=True)
        self._thread = t
        t.start()

    @staticmethod
    def _put(q, stop, item):
        """Queue.put that stays responsive to close()/reset()."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _place_batch(self, batch):
        rows = None

        def place(name, arr):
            value = arr._data() if isinstance(arr, NDArray) else arr
            nonlocal rows
            if rows is None and not is_preplaced(value, self._sharding):
                rows = int(value.shape[0])
            placed = place_batch_array(
                self.mesh, self.data_axes, self.distributed, name, value,
                sharding=self._sharding)
            return NDArray(placed)

        names_d = [d[0] if isinstance(d, tuple) else d.name
                   for d in (self.provide_data or [])]
        names_l = [d[0] if isinstance(d, tuple) else d.name
                   for d in (self.provide_label or [])]
        data = [place(names_d[i] if i < len(names_d) else "data%d" % i, a)
                for i, a in enumerate(batch.data or [])]
        label = [place(names_l[i] if i < len(names_l) else "label%d" % i, a)
                 for i, a in enumerate(batch.label or [])]
        if self.distributed and rows is not None:
            if self._local_rows is None:
                self._local_rows = rows
            elif rows != self._local_rows:
                raise MXNetError(
                    "DeviceQueueIter: local batch size changed mid-stream "
                    "(%d -> %d); pad or discard the tail batch so every "
                    "rank keeps a constant shape" % (self._local_rows, rows))
        out = DataBatch(data, label or None, pad=batch.pad,
                        index=batch.index,
                        provide_data=batch.provide_data,
                        provide_label=batch.provide_label)
        return out

    def _worker(self, q, stop):
        try:
            while not stop.is_set():
                try:
                    batch = self.data_iter.next()
                except StopIteration:
                    self._put(q, stop, _END)
                    return
                placed = self._place_batch(batch)
                profiler.h2d_record(batches=1, queue_depth=q.qsize())
                if not self._put(q, stop, placed):
                    return
        except BaseException as e:  # surfaced on the consumer thread
            self._exc = e
            self._put(q, stop, _ABORT)

    # -- consumer ------------------------------------------------------------
    def next(self):
        if self._closed:
            raise MXNetError("DeviceQueueIter: iterator is closed")
        self._ensure_started()
        if self._passthrough:
            return self.data_iter.next()
        t0 = time.perf_counter()
        item = self._q.get()
        profiler.h2d_record(stall_feed=time.perf_counter() - t0)
        if item is _END:
            # leave a sentinel for repeated next() calls post-epoch
            self._q.put(_END)
            raise StopIteration
        if item is _ABORT:
            self._q.put(_ABORT)  # repeated next() keeps raising
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        if self.distributed and not self._checked_agreement:
            # ONE main-thread collective on the first batch: every rank
            # must agree on its local rows before compiled steps with
            # cross-host collectives start (a mismatch builds
            # inconsistent global programs — a silent hang). Collectives
            # must never run on the worker thread: they would interleave
            # with the step's own collectives in arbitrary order. After
            # this, the pipeline relies on the constant-local-batch
            # invariant (_place_batch raises on a mid-stream change):
            # sources feeding a dist job MUST pad or discard tail
            # batches, because a rank that raises here cannot stop its
            # peers' already-dispatched collectives.
            import jax

            if jax.process_count() > 1 and self._local_rows is not None:
                if self._group is not None:
                    self._group._check_local_batch_agreement(
                        [self._local_rows])
                else:
                    from .. import dist

                    mine = np.asarray([self._local_rows], np.int32)
                    rows = dist.allgather(mine)
                    if not (rows == mine[None, :]).all():
                        raise MXNetError(
                            "DeviceQueueIter: local batch size %d differs "
                            "across workers (per-rank sizes %s); pad or "
                            "discard the tail batch so every rank agrees"
                            % (self._local_rows, rows.reshape(-1).tolist()))
            self._checked_agreement = True
        self._current_batch = item
        return item

    def iter_next(self):
        try:
            self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self._current_batch.data

    def getlabel(self):
        return self._current_batch.label

    def getindex(self):
        return self._current_batch.index

    def getpad(self):
        return self._current_batch.pad

    # -- lifecycle -----------------------------------------------------------
    def _shutdown(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            # drain so a worker blocked in put() can observe the stop
            # flag; bounded — a worker wedged inside the SOURCE
            # iterator's next() is a daemon thread and may be abandoned
            deadline = time.monotonic() + timeout
            while t.is_alive() and time.monotonic() < deadline:
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    pass
                t.join(timeout=0.05)
        self._thread = None

    def reset(self):
        """Restart from the top of the (reset) source iterator — valid
        after StopIteration AND after abandoning an epoch mid-stream."""
        if self._closed:
            raise MXNetError("DeviceQueueIter: iterator is closed")
        if self._passthrough or self._thread is None:
            self.data_iter.reset()
            return
        self._shutdown()
        self.data_iter.reset()
        self._current_batch = None
        self._start()

    def close(self):
        """Stop the worker, drop queued device batches, close the source
        iterator if it supports close() (unless built with
        ``close_source=False``). Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._shutdown()
        self._q = queue.Queue()  # drop device-batch references
        self._current_batch = None
        if self._close_source:
            inner_close = getattr(self.data_iter, "close", None)
            if callable(inner_close):
                inner_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
