"""Device-mesh construction helpers.

Reference counterpart: none directly — this replaces the device-placement
roles of KVStore/PlaceDevice with ``jax.sharding.Mesh`` axes. Convention:

- ``dp``: data parallel (batch axis)      — gradients psum over it
- ``tp``: tensor parallel (hidden axis)   — per-layer collectives
- ``pp``: pipeline stages                 — collective_permute between
- ``sp``: sequence/context parallel       — ring attention axis

Single-host: all local devices on one mesh. Multi-host: call
``jax.distributed.initialize`` first (tools/launch.py analogue), then the
global device list forms the mesh with DCN on the outermost axis.
"""
from __future__ import annotations

import numpy as np


def make_mesh(axes=None, devices=None):
    """Build a Mesh from axis spec {name: size}; -1 means 'rest'."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"dp": len(devices)})
    sizes = list(axes.values())
    n_known = 1
    for s in sizes:
        if s != -1:
            n_known *= s
    if -1 in sizes:
        sizes[sizes.index(-1)] = len(devices) // n_known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError("mesh axes %r need %d devices, have %d" % (axes, total, len(devices)))
    arr = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


_DEFAULT_MESH = None


def default_mesh():
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = make_mesh()
    return _DEFAULT_MESH


def set_default_mesh(mesh):
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh
