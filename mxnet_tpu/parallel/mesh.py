"""Device-mesh construction helpers.

Reference counterpart: none directly — this replaces the device-placement
roles of KVStore/PlaceDevice with ``jax.sharding.Mesh`` axes. Convention:

- ``dp``: data parallel (batch axis)      — gradients psum over it
- ``mp``: tensor/model parallel (hidden axis) — per-layer psums; the
  megatron column/row sharding of models/transformer.py (ISSUE 20).
  ``tp`` is the legacy alias some tests still build meshes with; new
  code uses ``mp``, and the transformer resolves whichever the mesh has
- ``tp``: tensor parallel (legacy alias of ``mp``)
- ``pp``: pipeline stages                 — collective_permute between
- ``sp``: sequence/context parallel       — ring attention axis

Single-host: all local devices on one mesh. Multi-host: call
``jax.distributed.initialize`` first (tools/launch.py analogue), then the
global device list forms the mesh with DCN on the outermost axis.
"""
from __future__ import annotations

import numpy as np


def make_mesh(axes=None, devices=None):
    """Build a Mesh from axis spec {name: size}; -1 means 'rest'."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {"dp": len(devices)})
    sizes = list(axes.values())
    n_known = 1
    for s in sizes:
        if s != -1:
            n_known *= s
    if -1 in sizes:
        sizes[sizes.index(-1)] = len(devices) // n_known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError("mesh axes %r need %d devices, have %d" % (axes, total, len(devices)))
    arr = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(axes.keys()))


def mp_size():
    """The strictly-validated ``MXNET_MP_SIZE`` knob (>= 1 integer;
    nonsense raises naming the knob)."""
    from .. import config

    return config.get_positive_int("MXNET_MP_SIZE")


def train_mesh(devices=None, mp=None):
    """The 2-D ``(dp, mp)`` training/serving mesh (ISSUE 20): the
    devices split into ``dp = N // mp`` data-parallel groups of ``mp``
    model shards each, with ``mp`` innermost so a model-parallel group
    sits on adjacent devices (ICI-neighbors on a real slice).

    ``mp=None`` consults ``MXNET_MP_SIZE``; ``mp=1`` yields the plain
    ``{"dp": N}`` 1-axis mesh — bit-identical to the pre-ISSUE-20
    data-parallel path (no second axis for pjit to partition over).
    ``mp`` must divide the device count; anything else raises.
    """
    import jax

    from ..base import MXNetError

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    mp = mp_size() if mp is None else int(mp)
    if mp < 1:
        raise MXNetError("train_mesh: mp=%r must be >= 1" % (mp,))
    if n % mp != 0:
        raise MXNetError(
            "train_mesh: MXNET_MP_SIZE=%d must divide the device "
            "count %d" % (mp, n))
    if mp == 1:
        return make_mesh({"dp": n}, devices=devices)
    return make_mesh({"dp": n // mp, "mp": mp}, devices=devices)


_DEFAULT_MESH = None


def default_mesh():
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = make_mesh()
    return _DEFAULT_MESH


def set_default_mesh(mesh):
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh
