"""Pipeline parallelism (GPipe schedule) over a ``pp`` mesh axis.

Reference counterpart: **absent** (SURVEY §2.4: "Pipeline parallelism —
Absent... optional: shard_map + collective-permute pipeline over stages").
This implements that optional TPU-native generalization: stages live on
submeshes along ``pp``; activations ride ``lax.ppermute`` (ICI
collective-permute); microbatches fill the pipe GPipe-style. Backward is
jax autodiff through the schedule — ppermute transposes to the reverse
permute, giving the textbook reverse pipe.

``pipeline_apply`` is the shard_map-inner building block (composable with
tp/sp inside a stage); ``pipeline`` wraps it standalone.

Schedule: ``n_micro + n_stages - 1`` ticks; at tick t stage 0 ingests
microbatch t, stage s computes microbatch ``t - s``, the last stage
retires microbatch ``t - (n_stages-1)``. Bubble fraction
``(n_stages-1)/(n_micro + n_stages - 1)`` — pick n_micro >= 4 * n_stages.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..util import shard_map as _shard_map

__all__ = ["pipeline_apply", "pipeline"]


def pipeline_apply(stage_fn, stage_params, x, *, axis_name="pp",
                   n_microbatches=None):
    """Run ``stage_fn(stage_params, act) -> act`` as a GPipe pipeline.

    Call *inside* shard_map. ``stage_params`` is this stage's slice (enter
    the enclosing shard_map with the stacked leading stage dim sharded
    P('pp', ...) and squeeze it). ``x``: (n_micro, mb, ...) microbatched
    input, replicated over ``pp``. Returns (n_micro, mb, ...) outputs
    (replicated over ``pp`` via a masked psum).
    """
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x.shape[0] if n_microbatches is None else n_microbatches
    mb_shape = x.shape[1:]

    state0 = jnp.zeros(mb_shape, x.dtype) + x[0] * 0   # varying like x
    ys0 = jnp.zeros_like(x)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, ys = carry
        # stage 0 ingests microbatch t (clamped; ticks past the last
        # microbatch push zeros through the drain phase)
        x_t = lax.dynamic_index_in_dim(x, jnp.minimum(t, n_micro - 1),
                                       keepdims=False)
        inp = jnp.where(stage == 0, x_t, state)
        out = stage_fn(stage_params, inp)
        # the last stage retires microbatch t-(n_stages-1)
        mi = t - (n_stages - 1)
        take = (stage == n_stages - 1) & (mi >= 0)
        ys = lax.cond(
            take,
            lambda ys: lax.dynamic_update_index_in_dim(
                ys, out.astype(ys.dtype), jnp.maximum(mi, 0), 0),
            lambda ys: ys, ys)
        state = lax.ppermute(out, axis_name, perm)
        return (state, ys), None

    total = n_micro + n_stages - 1
    (_, ys), _ = lax.scan(tick, (state0, ys0), jnp.arange(total))
    # replicate outputs to every stage (only the last stage holds them)
    return lax.psum(jnp.where(stage == n_stages - 1, ys, 0.0), axis_name)


def pipeline(stage_fn, stacked_params, x, mesh, *, axis_name="pp",
             n_microbatches=None, param_spec=None, data_spec=None):
    """Standalone GPipe: ``stacked_params`` leaves have a leading
    ``n_stages`` dim (sharded over ``axis_name``); ``x`` is the *global*
    (n_micro, mb, ...) input."""
    pspec = param_spec or jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    dspec = data_spec or P()

    def inner(sp, xin):
        local = jax.tree_util.tree_map(lambda a: a[0], sp)  # squeeze stage dim
        return pipeline_apply(stage_fn, local, xin, axis_name=axis_name,
                              n_microbatches=n_microbatches)

    return _shard_map(inner, mesh=mesh, in_specs=(pspec, dspec),
                      out_specs=P(), check_vma=False)(stacked_params, x)
