"""Sequence / context parallelism: ring attention and Ulysses all-to-all.

Reference counterpart: **none** — the reference (2017, SURVEY §5.7) handles
long sequences only via BucketingModule / fused RNN / memory mirroring.
These are the TPU-native generalizations mandated by the survey: scale
sequence length over a mesh axis (``sp``) with ICI collectives.

Design (How-to-Scale-Your-Model recipe):

- **Ring attention** (`ring_attention`): Q stays put, K/V chunks rotate
  around the ``sp`` ring via ``lax.ppermute`` (XLA lowers to ICI
  collective-permute, overlapped with the per-step attention matmuls).
  Online-softmax accumulation (running max ``m``, running sum ``l``,
  unnormalized accumulator) makes the per-chunk combine exact — the same
  math as flash attention's outer loop, so the result is bit-comparable
  to full attention up to fp associativity.
- **Ulysses** (`ulysses_attention`): ``lax.all_to_all`` reshards
  sequence-sharded activations to head-sharded, runs *local, full-sequence*
  attention per head group, then reshards back. Cheaper at moderate
  sequence lengths (2 all-to-alls vs (n-1) permutes); requires
  ``num_heads % axis_size == 0``.

Both inner functions are written to run *inside* an enclosing
``shard_map`` (composable with dp/tp axes); the module-level wrappers
build the ``shard_map`` for the common standalone case.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..util import shard_map as _shard_map

__all__ = [
    "ring_attention_inner", "ring_attention",
    "ulysses_attention_inner", "ulysses_attention",
    "full_attention",
]

_NEG_INF = -1e30  # finite -inf stand-in: keeps online-softmax NaN-free


def full_attention(q, k, v, *, causal=False, sm_scale=None, q_offset=0,
                   k_offset=0):
    """Plain softmax attention, (B, H, S, D) layout, fp32 softmax.

    ``q_offset``/``k_offset`` are the global positions of q[...,0,:] and
    k[...,0,:] — needed for causal masking of sequence *shards*.
    """
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[2])[:, None]
        ki = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _attend_chunk(q, k, v, m, l, acc, *, scale, causal, q_offset, k_offset):
    """One online-softmax accumulation step against a K/V chunk.

    m: (B,H,Sq) running max; l: (B,H,Sq) running denominator;
    acc: (B,H,Sq,D) unnormalized numerator. All fp32.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[2])[:, None]
        ki = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    m_step = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_step)
    alpha = jnp.exp(m - m_new)                      # rescale old state
    p = jnp.exp(s - m_new[..., None])               # (B,H,Sq,Sk)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def ring_attention_inner(q, k, v, *, axis_name="sp", causal=False,
                         sm_scale=None):
    """Ring attention over a sequence-sharded axis; call inside shard_map.

    q, k, v: (B, H, S_local, D) — the local sequence shard. Returns the
    local output shard (B, H, S_local, D) in q.dtype.
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    d = q.shape[-1]
    s_local = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    q32 = q.astype(jnp.float32)

    # derive the accumulators from q/k so they carry the same device-varying
    # axes as the loop outputs (jax>=0.9 vma tracking rejects a constant
    # carry combined with shard_map-varying values)
    zero_qk = q32[..., 0] * 0 + k.astype(jnp.float32)[..., 0, 0][..., None] * 0
    m0 = zero_qk + _NEG_INF
    l0 = zero_qk
    acc0 = jnp.zeros_like(q32) + zero_qk[..., None]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(t, carry):
        m, l, acc, kc, vc = carry
        # chunk currently held = the one originating at device (my_idx - t);
        # under causal masking, future chunks (src > my_idx) contribute
        # exactly zero via the per-element mask in _attend_chunk
        src = (my_idx - t) % n
        m, l, acc = _attend_chunk(
            q32, kc.astype(jnp.float32), vc, m, l, acc,
            scale=scale, causal=causal,
            q_offset=my_idx * q.shape[2], k_offset=src * s_local)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m, l, acc, kc, vc

    m, l, acc, _, _ = lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, *, axis_name="sp", causal=False,
                   sm_scale=None, batch_axis=None):
    """Standalone ring attention: shard seq (dim 2) over ``axis_name``.

    q, k, v: *global* (B, H, S, D) arrays; S % axis_size == 0. With
    ``batch_axis`` the batch dim additionally shards over that mesh axis
    (dp composition).
    """
    from .mesh import default_mesh

    mesh = mesh or default_mesh()
    spec = P(batch_axis, None, axis_name, None)
    fn = functools.partial(ring_attention_inner, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale)
    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)


def ulysses_attention_inner(q, k, v, *, axis_name="sp", causal=False,
                            sm_scale=None, attn_fn=None):
    """Ulysses sequence parallelism; call inside shard_map.

    Input is sequence-sharded (B, H, S_local, D); all-to-all swaps the
    shard dim to heads (B, H/n, S, D), local full attention runs on the
    complete sequence, and a second all-to-all swaps back.
    ``attn_fn(q,k,v,causal,sm_scale)`` defaults to `full_attention` —
    pass the Pallas flash kernel for the fused path.
    """
    def to_heads(x):   # (B, H, S/n, D) -> (B, H/n, S, D)
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(x):     # (B, H/n, S, D) -> (B, H, S/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    if attn_fn is None:
        out = full_attention(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    else:
        out = attn_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return to_seq(out)


def ulysses_attention(q, k, v, mesh=None, *, axis_name="sp", causal=False,
                      sm_scale=None, batch_axis=None, attn_fn=None):
    """Standalone Ulysses attention on global (B, H, S, D) arrays."""
    from .mesh import default_mesh

    mesh = mesh or default_mesh()
    spec = P(batch_axis, None, axis_name, None)
    fn = functools.partial(ulysses_attention_inner, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale, attn_fn=attn_fn)
    return _shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)(q, k, v)
