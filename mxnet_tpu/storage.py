"""Pooled host storage manager (Python front end).

Reference counterpart: ``include/mxnet/storage.h`` Storage::Alloc/Free
over the pooled manager (src/storage/pooled_storage_manager.h). Device
(HBM) memory belongs to XLA; this pool recycles *host* staging buffers
(infeed batches, recordio scratch, checkpoint shards) through the native
allocator in src/storage.cc, avoiding malloc churn in the input pipeline.
"""
from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

from . import _native
from .base import MXNetError

__all__ = ["StoragePool", "default_pool"]


class StoragePool:
    """Size-bucketed recycling allocator over the native pool."""

    def __init__(self, max_cached_bytes=1 << 30):
        lib = _native.get_lib()
        if lib is None:
            raise MXNetError("native runtime unavailable: %s"
                             % (_native.last_error() or "build failed"))
        self._lib = lib
        self._handle = lib.MXTStoragePoolCreate(max_cached_bytes)

    def __del__(self):
        handle, self._handle = getattr(self, "_handle", None), None
        if handle and self._lib is not None:
            self._lib.MXTStoragePoolFree(handle)

    def alloc(self, size):
        """Raw aligned allocation; returns an int address (release() it)."""
        ptr = self._lib.MXTStorageAlloc(self._handle, size)
        if not ptr:
            raise MemoryError("StoragePool.alloc(%d) failed" % size)
        return ptr

    def release(self, ptr, size):
        self._lib.MXTStorageRelease(self._handle, ptr, size)

    def empty(self, shape, dtype=np.float32):
        """A numpy array over pooled memory; the buffer returns to the
        pool when the array (and any views of it) are garbage collected."""
        dtype = np.dtype(dtype)
        nelem = int(np.prod(shape))
        # allocate at least one element so zero-sized arrays still map to
        # a valid buffer; count= keeps the logical length exact
        nbytes = max(nelem, 1) * dtype.itemsize
        ptr = self.alloc(nbytes)
        buf = (ctypes.c_char * nbytes).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype, count=nelem).reshape(shape)
        return _wrap(arr, _Guard(self, ptr, nbytes))

    def stats(self):
        vals = [ctypes.c_int64() for _ in range(4)]
        self._lib.MXTStoragePoolStats(self._handle, *[ctypes.byref(v) for v in vals])
        return {
            "live_bytes": vals[0].value, "cached_bytes": vals[1].value,
            "hits": vals[2].value, "misses": vals[3].value,
        }

    def drain(self):
        self._lib.MXTStoragePoolDrain(self._handle)


class _Guard:
    """Returns the buffer to the pool on GC of the owning array."""

    def __init__(self, pool, ptr, nbytes):
        self._pool, self._ptr, self._nbytes = pool, ptr, nbytes

    def __del__(self):
        self._pool.release(self._ptr, self._nbytes)


class _PooledNDArray(np.ndarray):
    """ndarray subclass carrying the pool guard through views."""

    def __array_finalize__(self, obj):
        if obj is not None:
            self._pool_guard = getattr(obj, "_pool_guard", None)


def _wrap(arr, guard):
    out = arr.view(_PooledNDArray)
    out._pool_guard = guard
    return out


_DEFAULT = None
_LOCK = threading.Lock()


def default_pool():
    global _DEFAULT
    if _DEFAULT is None:
        with _LOCK:
            if _DEFAULT is None:
                cap = int(os.environ.get("MXNET_TPU_HOST_POOL_BYTES",
                                         str(1 << 30)))
                _DEFAULT = StoragePool(cap)
    return _DEFAULT
