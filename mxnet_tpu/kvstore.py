"""KVStore — data-parallel parameter synchronization.

Reference counterpart: ``include/mxnet/kvstore.h`` + ``src/kvstore/``
(SURVEY §2.4/§2.6): local/device tree-reduce Comm, NCCL, ps-lite dist
workers/servers. TPU-native design: a single-process KVStore keeps the full
Init/Push/Pull/row-sparse/updater surface for API parity; the reduction
over "devices" is a jnp tree-sum (one fused XLA op). ``kvstore='tpu'``
additionally carries mesh metadata so Module's executor shards the batch
over the data axis of a `jax.sharding.Mesh` and gradients all-reduce over
ICI *inside* the compiled step (the reference's priority-scheduled NCCL
overlap becomes XLA latency hiding) — no server process exists; multi-host
(DCN) uses the same mesh with jax.distributed initialization.

Gradient compression API (2-bit + error feedback, ref
src/kvstore/gradient_compression.cc) is kept: quantization runs as jitted
XLA ops between reduce and update.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .base import MXNetError
from .ndarray import ndarray as nd
from .ndarray.ndarray import NDArray


# ---------------------------------------------------------------------------
# 2-bit gradient compression — the WIRE format (ref:
# gradient_compression.h:37-133 SetTwoBitCompression/Quantize/Dequantize).
# Shared by every tier: the local store runs quantize->dequantize as a
# fidelity simulation, the server tier (kvstore_server.ServerKVStore)
# ships the packed payload across the wire and dequantizes server-side.
# ---------------------------------------------------------------------------
_COMPRESSION_KEYS = frozenset(("type", "threshold"))


def validate_compression_params(compression_params):
    """Validated copy of a set_gradient_compression() params dict.

    Fails loudly (MXNET_TRACKER_*-style, ISSUE 4 satellite): unknown
    keys and a non-finite / non-positive threshold are configuration
    bugs that would otherwise silently train with the default."""
    if not isinstance(compression_params, dict):
        raise MXNetError("set_gradient_compression expects a dict, got %r"
                         % type(compression_params).__name__)
    unknown = sorted(set(compression_params) - _COMPRESSION_KEYS)
    if unknown:
        raise MXNetError(
            "set_gradient_compression: unknown key(s) %s (supported: "
            "type, threshold)" % ", ".join(map(repr, unknown)))
    if compression_params.get("type") not in ("2bit",):
        raise MXNetError("unsupported compression type %r"
                         % compression_params.get("type"))
    threshold = compression_params.get("threshold", 0.5)
    if isinstance(threshold, bool) or not isinstance(
            threshold, (int, float, np.floating, np.integer)):
        raise MXNetError(
            "set_gradient_compression: threshold must be a finite float "
            "> 0, got %r" % (threshold,))
    threshold = float(threshold)
    if not 0.0 < threshold < float("inf"):  # also rejects NaN
        raise MXNetError(
            "set_gradient_compression: threshold must be a finite float "
            "> 0, got %r" % (threshold,))
    return {"type": "2bit", "threshold": threshold}


_QUANT_JIT = {}


def two_bit_pack_core(a, threshold):
    """Traceable 2-bit pack: ternary threshold, 4 codes per byte.
    Returns ``(packed uint8, quantized values)``. Pure jnp — callable
    from inside any jit/pjit program (the local tier's kernels below
    AND the fused ZeRO step's wire-compression path share it)."""
    import jax.numpy as jnp

    pos = a >= threshold
    neg = a <= -threshold
    quant = jnp.where(pos, threshold,
                      jnp.where(neg, -threshold, 0.0)).astype(a.dtype)
    codes = pos.astype(jnp.uint8) | (neg.astype(jnp.uint8) << 1)
    flat = codes.reshape(-1)
    pad = (-flat.size) % 4
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), jnp.uint8)])
    q4 = flat.reshape(-1, 4)
    packed = (q4[:, 0] | (q4[:, 1] << 2)
              | (q4[:, 2] << 4) | (q4[:, 3] << 6))
    return packed, quant


def two_bit_round_trip_core(g, res, threshold):
    """Traceable quantize→dequantize with error feedback: the value
    ``g`` would have after crossing the packed 2-bit wire, plus the new
    residual. Round-trips through the ACTUAL packed codes, so fidelity
    matches the server-tier wire bit-for-bit."""
    import jax.numpy as jnp

    a = g + res
    packed, quant = two_bit_pack_core(a, threshold)
    t = jnp.asarray(threshold, a.dtype)
    codes = jnp.stack([(packed >> (2 * j)) & 3 for j in range(4)],
                      axis=1).reshape(-1)[:a.size]
    q = jnp.where(codes == 1, t,
                  jnp.where(codes == 2, -t,
                            jnp.zeros((), a.dtype))).reshape(a.shape)
    return q, a - quant


def _two_bit_kernels():
    """The jitted 2-bit cores (compiled once per (shape, dtype,
    threshold)): ``quantize`` — error-feedback add, ternary threshold,
    4-codes-per-byte packing — for the wire path, and ``sim`` — the
    same packing round-tripped through the on-device unpack — for the
    local tier, which trains on exactly the packed wire codes without
    ever leaving the device."""
    fns = _QUANT_JIT.get("fns")
    if fns is None:
        import functools

        import jax

        @functools.partial(jax.jit, static_argnums=(2,))
        def quantize(g, res, threshold):
            a = g + res
            packed, quant = two_bit_pack_core(a, threshold)
            return packed, a - quant

        @functools.partial(jax.jit, static_argnums=(2,))
        def sim(g, res, threshold):
            return two_bit_round_trip_core(g, res, threshold)

        fns = _QUANT_JIT["fns"] = (quantize, sim)
    return fns


def two_bit_quantize(grad, residual, threshold):
    """Quantize ``grad + residual`` to 2-bit codes (0, +threshold ->
    0b01, -threshold -> 0b10), 4 values per byte — the ~16x-smaller
    wire payload. Returns ``(packed uint8 array of ceil(n/4) bytes,
    new_residual)``; the residual carries the quantization error into
    the next round (error feedback)."""
    g = np.asarray(grad)
    res = np.zeros(g.shape, g.dtype) if residual is None \
        else np.asarray(residual, g.dtype)
    packed, new_res = _two_bit_kernels()[0](g, res, float(threshold))
    return np.asarray(packed), np.asarray(new_res)


def two_bit_dequantize(packed, shape, dtype, threshold):
    """Unpack 2-bit codes back to {-threshold, 0, +threshold}. Pure
    numpy (the server side has no business compiling XLA programs for
    a bit-unpack)."""
    if isinstance(packed, (bytes, bytearray, memoryview)):
        packed = np.frombuffer(packed, np.uint8)
    else:
        packed = np.asarray(packed, np.uint8)
    shape = tuple(shape)
    n = int(np.prod(shape)) if shape else 1
    codes = np.empty((packed.size, 4), np.uint8)
    for j in range(4):
        codes[:, j] = (packed >> (2 * j)) & 3
    flat = codes.reshape(-1)[:n]
    t = np.dtype(dtype).type(threshold)
    out = np.zeros(n, dtype)
    out[flat == 1] = t
    out[flat == 2] = -t
    return out.reshape(shape)


def _key_list(key):
    if isinstance(key, (str, int)):
        return [key], True
    return list(key), False


def _val_list(value, nkeys):
    """Normalize to list-of-lists: per key, list of per-device values."""
    if isinstance(value, NDArray):
        return [[value]]
    if isinstance(value, (list, tuple)):
        if value and isinstance(value[0], NDArray):
            if nkeys == 1:
                return [list(value)]
            if len(value) == nkeys:
                return [[v] for v in value]
            raise MXNetError("kvstore: value count %d mismatches keys %d" % (len(value), nkeys))
        return [list(v) for v in value]
    raise MXNetError("kvstore: bad value type %r" % type(value))


class KVStore:
    """In-process kvstore ('local'/'device'/'tpu' single-host tiers)."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._str_keys = {}

    # -- init/push/pull ------------------------------------------------------
    def init(self, key, value):
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k in self._store:
                continue
            self._store[k] = vlist[0].copy()

    def push(self, key, value, priority=0):
        """Aggregate per-device grads and apply updater (ref semantics:
        Comm::Reduce then updater, src/kvstore/kvstore_local.h)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("kvstore: key %r not initialized" % (k,))
            agg = self._reduce(vlist)
            if self._compression_params is not None:
                agg = self._compress_decompress(k, agg)
            agg = self._to_store_device(k, agg)
            if self._updater is not None:
                self._updater(self._normalize_key(k), agg, self._store[k])
            else:
                self._store[k] += agg

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, single = _key_list(key)
        if out is None:
            raise MXNetError("kvstore.pull requires out=")
        outs = _val_list(out, len(keys))
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("kvstore: key %r not initialized" % (k,))
            src = self._store[k]
            for o in olist:
                src.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (ref: KVStore::PullRowSparse)."""
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, _ = _key_list(key)
        outs = _val_list(out, len(keys))
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, olist in zip(keys, outs):
            src = self._store[k]
            # per-key broadcast (a shared single-rid list must not be
            # sized off key 0's target count — keys can differ)
            key_rids = rids * len(olist) \
                if len(rids) == 1 and len(olist) > 1 else rids
            for o, rid in zip(olist, key_rids):
                # unique-sort requested ids first (ref kvstore_local.h
                # PullRowSparse does the same); the row_sparse result
                # then satisfies the canonical unique-index invariant
                # without the constructor summing repeated requests
                ids = np.unique(np.asarray(rid.asnumpy(), np.int64))
                if ids.size and (ids[0] < 0 or ids[-1] >= src.shape[0]):
                    # same contract as the server tier: wrong data
                    # (clip to last row) is worse than an error
                    raise MXNetError(
                        "row_sparse_pull: row_ids out of range for key "
                        "%r: [%d, %d] vs %d rows"
                        % (k, int(ids[0]), int(ids[-1]), src.shape[0]))
                rid = nd.array(ids)
                taken = nd.invoke("take", [src, rid], {"axis": 0, "mode": "clip"})
                from .ndarray.sparse import RowSparseNDArray

                if isinstance(o, RowSparseNDArray):
                    # rid is already unique-sorted above — construct
                    # directly, skipping row_sparse_array's re-canonicalize
                    newo = RowSparseNDArray(taken, rid.astype(np.int64),
                                            src.shape, ctx=o.ctx)
                    o._rebind_sparse(newo)
                else:
                    # dense out: scatter rows into place, others zero
                    dense = nd.zeros(src.shape, ctx=o.ctx, dtype=src.dtype)
                    dense[rid] = taken
                    dense.copyto(o)
        return

    def _to_store_device(self, k, agg):
        """Align the reduced gradient with the store value's device — the
        pushed grads may live on accelerator while the store was init'ed
        from host-context params (ref: Comm reduce targets the store's
        pinned ctx, comm.h). Tolerates numpy-backed values (whose .device
        is absent or a string) by uploading them."""
        import jax

        dev = getattr(self._store[k]._data(), "device", None)
        if dev is None or not hasattr(dev, "platform"):
            return agg  # store itself is host-backed: nothing to align to
        src = getattr(agg._data(), "device", None)
        if src is not dev:
            agg = NDArray(jax.device_put(agg._data(), dev), ctx=self._store[k].ctx)
        return agg

    # -- reduction -----------------------------------------------------------
    @staticmethod
    def _reduce(vlist):
        """Tree-sum per-device values onto device 0 (Comm::Reduce parity,
        src/kvstore/comm.h:56 — the device transfer is jax device_put).
        Row-sparse gradients aggregate sparsely — indices/values concat,
        never densified (ref: comm.h ReduceRowSparse)."""
        if len(vlist) == 1:
            return vlist[0]
        from .ndarray import sparse as nd_sparse

        if all(isinstance(v, nd_sparse.RowSparseNDArray) for v in vlist):
            total = vlist[0]
            for v in vlist[1:]:
                total = nd_sparse.add(total, v)
            return total
        import jax

        dev = vlist[0].ctx.jax_device()
        total = vlist[0]._data()
        for v in vlist[1:]:
            total = total + jax.device_put(v._data(), dev)
        return NDArray(total, ctx=vlist[0].ctx)

    # -- optimizer/updater ---------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Run optimizer "on the store" (ref: server-side optimizer via
        SendCommandToServers; here the store is in-process)."""
        from . import optimizer as opt

        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _normalize_key(self, k):
        if isinstance(k, str):
            if k not in self._str_keys:
                self._str_keys[k] = len(self._str_keys)
            return k
        return k

    # -- gradient compression ------------------------------------------------
    def set_gradient_compression(self, compression_params):
        self._compression_params = validate_compression_params(
            compression_params)
        self._residuals = {}

    def _compress_decompress(self, key, agg):
        """2-bit quantization with error feedback, round-tripped through
        the SAME packed wire codes the server tier ships — but in one
        jitted XLA program with a device-resident residual, so the hot
        path never does a device->host->device round trip per key per
        step (the wire path's numpy contract lives in two_bit_quantize /
        two_bit_dequantize; this shares its packing core)."""
        import jax.numpy as jnp

        threshold = self._compression_params["threshold"]
        g = agg._data()
        res = self._residuals.get(key)
        if res is None:
            res = jnp.zeros(jnp.shape(g), jnp.result_type(g))
        q, self._residuals[key] = _two_bit_kernels()[1](
            g, res, float(threshold))
        return NDArray(q, ctx=agg.ctx)

    # -- distributed surface -------------------------------------------------
    @property
    def rank(self):
        import jax

        return jax.process_index()

    @property
    def num_workers(self):
        import jax

        return jax.process_count()

    def barrier(self):
        nd.waitall()

    def num_dead_node(self, node_id=0, timeout=60):
        """Count of dead workers (ref: KVStore::get_num_dead_node,
        include/mxnet/kvstore.h:330-340). Always 0 for in-process
        stores; DistKVStore consults the coordination-service
        heartbeats."""
        del node_id, timeout
        return 0

    def set_barrier_before_exit(self, barrier_before_exit=True):
        """ref: barrier_before_exit_, kvstore.h:290-297 — honored by
        dist stores at interpreter exit (bounded-timeout barrier)."""
        self._barrier_before_exit = bool(barrier_before_exit)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("kvstore: no updater to save")
        from .checkpoint import atomic_write_bytes

        # tmp-fsync-rename: a crash mid-write must never leave a torn
        # state file that load_optimizer_states half-parses (ISSUE 3)
        atomic_write_bytes(fname, self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("kvstore: no updater")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


class TPUKVStore(KVStore):
    """kvstore='tpu': device-mesh data parallelism.

    Single-host: identical in-process semantics; Module detects this type
    and compiles its train step with batch sharded over the mesh data axis,
    so gradient all-reduce is a ``psum`` over ICI *inside* XLA — push/pull
    here only see the already-reduced result. Multi-host: same program with
    jax.distributed (DCN joins the mesh); see parallel/mesh.py.
    """

    def __init__(self, kv_type="tpu"):
        super().__init__(kv_type)
        self._mesh = None  # attached by Module when the fused step binds

    def attach_mesh(self, mesh):
        """Record the device mesh whose data axis carries this store's
        reductions (set by Module's fused SPMD group)."""
        self._mesh = mesh

    @property
    def mesh(self):
        return self._mesh


class DistKVStore(TPUKVStore):
    """dist_sync / dist_async / dist_sync_device over jax.distributed.

    Reference counterpart: KVStoreDist worker + KVStoreDistServer
    (kvstore_dist.h:49, kvstore_dist_server.h:113). Serverless TPU
    design: every worker joined one jax.distributed job (launched by
    tools/launch.py); ``push`` reduces locally and buffers; the first
    ``pull``/``barrier`` flushes every pending key in ONE flattened XLA
    collective over the DCN mesh axis — the server-side merge-buffer
    aggregation becomes a compiled sum, batched like the reference's
    16-key push aggregation (model.py:106-124). The
    updater then runs identically on every worker (replacing the
    server-side optimizer), so weights stay bit-identical without a
    pull round-trip. dist_async maps to the same synchronous collective
    (no stale-gradient tier exists on a single-controller mesh).
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        import atexit

        from . import dist

        if kv_type == "dist_async":
            import warnings

            # the API accepts the mode but delivers different semantics —
            # say so loudly rather than silently (VERDICT r2 weak #5)
            warnings.warn(
                "kvstore 'dist_async' runs with synchronous semantics on "
                "the single-controller mesh (no stale-gradient tier); "
                "updates are collective and deterministic, matching "
                "dist_sync", stacklevel=3)
        dist.init_from_env()
        self._pending = {}
        self._barrier_before_exit = True
        atexit.register(self._exit_barrier)

    def _exit_barrier(self):
        if getattr(self, "_barrier_before_exit", False):
            from . import dist

            # bounded barrier FIRST: if a peer is dead it fails within the
            # timeout and we skip the unbounded collective flush (which
            # would hang forever waiting for the dead worker). When it
            # succeeds, every live worker is inside its own exit hook and
            # will run the matching flush.
            if dist.exit_barrier():
                self._flush()

    def num_dead_node(self, node_id=0, timeout=60):
        from . import dist

        return dist.get_num_dead_node(node_id, timeout)

    def push(self, key, value, priority=0):
        """Local reduce (+ optional 2-bit quantization, worker-side as in
        kvstore_dist.h:346) then *defer*: pushes buffer until the first
        pull/barrier, when ALL pending keys cross the wire in ONE
        flattened XLA collective — the TPU analogue of the reference's
        16-key push aggregation (model.py:106-124)."""
        keys, _ = _key_list(key)
        vals = _val_list(value, len(keys))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("kvstore: key %r not initialized" % (k,))
            if k in self._pending:
                # double push of one key before any pull: preserve
                # accumulate semantics by flushing the first round
                self._flush()
            agg = self._reduce(vlist)
            from .ndarray.sparse import RowSparseNDArray

            if (self._compression_params is not None
                    and not isinstance(agg, RowSparseNDArray)):
                # ref parity: compression applies to dense keys only;
                # row-sparse crosses the wire uncompressed
                # (kvstore_dist.h EncodeCompressedKey vs EncodeRowSparseKey)
                agg = self._compress_decompress(k, agg)
            if isinstance(agg, RowSparseNDArray):
                # keep row-sparse grads sparse across the wire (ref
                # EncodeRowSparseKey, kvstore_dist.h:147-346): snapshot
                # (values, row_ids); _flush exchanges only stored rows
                self._pending[k] = (
                    "rsp",
                    np.asarray(agg.data._data()),
                    np.asarray(agg.indices._data(), np.int64),
                    tuple(agg.shape), agg.ctx)
            else:
                # snapshot the (immutable) array now: the caller may
                # overwrite its gradient in place before the flushing pull
                self._pending[k] = (agg._data(), agg.ctx)

    def _flush(self):
        """One cross-worker collective for every pending key."""
        if not self._pending:
            return
        from . import dist

        pending, self._pending = self._pending, {}
        # discriminate structurally: only row-sparse entries carry a str
        # tag in slot 0 (dense slot 0 is a device array, and array==str
        # comparison semantics vary across numpy/JAX versions)
        rsp = {k: pending.pop(k) for k in
               [k for k, v in pending.items() if isinstance(v[0], str)]}
        if rsp:
            self._flush_row_sparse(rsp)
        if not pending:
            return
        # group by dtype so the flattened concat is bit-exact per key;
        # concat on host — the collective is host-mediated anyway, so a
        # device-side concat would only add a round-trip
        by_dtype = {}
        for k, (arr, ctx) in pending.items():
            by_dtype.setdefault(np.dtype(arr.dtype), []).append(k)
        for dt, keys in by_dtype.items():
            arrs = [np.asarray(pending[k][0]) for k in keys]
            flat = (np.concatenate([a.reshape(-1) for a in arrs])
                    if len(arrs) > 1 else arrs[0].reshape(-1))
            total = dist.allreduce(flat)
            off = 0
            for k, a in zip(keys, arrs):
                size = int(np.prod(a.shape)) if a.ndim else 1
                agg = NDArray(total[off:off + size].reshape(a.shape),
                              ctx=pending[k][1])
                off += size
                agg = self._to_store_device(k, agg)
                if self._updater is not None:
                    self._updater(self._normalize_key(k), agg, self._store[k])
                else:
                    self._store[k] += agg

    def _flush_row_sparse(self, rsp):
        """Cross-worker aggregation of pending row-sparse gradients.

        Each worker's ACTUAL (row_id, values) payload crosses the wire
        (ref kvstore_dist.h:147-346 EncodeRowSparseKey — the reference
        sends per-worker real nnz, never a padded maximum): one small
        nnz-matrix allgather, one id gather covering every key (ids are
        int32, cheap next to values), then one value gather per dtype,
        padded only to the largest TOTAL payload across workers. A key
        whose combined nnz reaches its dense row count ships its VALUES
        as a dense allreduce instead — degraded sparsity must never
        cost more wire than the dense flush (the round-3 tier paid
        nworkers x max_nnz x width per key). Either way the emitted
        aggregate carries exactly the UNION of rows workers touched, so
        lazy sparse optimizers (optimizer.py lazy_update) never see
        phantom rows.

        Row ids cross the wire as int32 (JAX canonicalizes int64 down
        anyway without x64); tables beyond 2^31 rows are rejected
        rather than silently corrupted."""
        from . import dist
        from .ndarray.sparse import RowSparseNDArray, _canonicalize

        keys = sorted(rsp)
        kidx = {k: i for i, k in enumerate(keys)}
        for k in keys:
            if rsp[k][3][0] > np.iinfo(np.int32).max:
                raise MXNetError(
                    "row-sparse dist push: %r has %d rows; the int32 "
                    "wire format supports up to 2^31-1"
                    % (k, rsp[k][3][0]))
        my_nnz = np.asarray([rsp[k][2].shape[0] for k in keys], np.int64)
        nnz_all = np.asarray(dist.allgather(my_nnz), np.int64)  # (W, K)
        nworkers = nnz_all.shape[0]
        combined = nnz_all.sum(axis=0)

        # ids: ONE gather over all keys, padded to the max total nnz
        max_tot = int(nnz_all.sum(axis=1).max())
        pid = np.full((max(max_tot, 1),), -1, np.int32)
        my_ids = (np.concatenate([rsp[k][2] for k in keys])
                  if len(keys) else np.zeros((0,), np.int64))
        pid[:len(my_ids)] = np.asarray(my_ids, np.int32)
        gathered_ids = dist.allgather(pid)

        # per (worker, key) id slices from the nnz matrix
        id_slices = {}
        offs = np.zeros((nworkers,), np.int64)
        for k in keys:
            ki = kidx[k]
            for wrk in range(nworkers):
                n = int(nnz_all[wrk, ki])
                io = int(offs[wrk])
                id_slices[(wrk, k)] = (
                    gathered_ids[wrk, io:io + n].astype(np.int64))
                offs[wrk] += n

        def _emit(k, all_vals, all_ids, shape, ctx):
            import jax.numpy as jnp

            m_vals, m_ids = _canonicalize(jnp.asarray(all_vals),
                                          jnp.asarray(all_ids))
            agg = RowSparseNDArray(NDArray(m_vals, ctx=ctx),
                                   NDArray(m_ids.astype("int64"), ctx=ctx),
                                   shape, ctx=ctx)
            if self._updater is not None:
                self._updater(self._normalize_key(k), agg, self._store[k])
            else:
                self._accumulate_rsp(k, agg)

        # wire heuristic only — semantics are identical on both paths
        dense_set = {k for k, c in zip(keys, combined)
                     if c >= rsp[k][3][0]}
        sparse_keys = [k for k in keys if k not in dense_set]

        # degraded keys: VALUES cross as one dense allreduce per dtype;
        # the emitted rows are still exactly the cross-worker union
        by_dtype = {}
        for k in sorted(dense_set):
            _tag, vals, ids, shape, ctx = rsp[k]
            dense = np.zeros(shape, vals.dtype)
            if ids.size:
                dense[ids] = vals.reshape((ids.shape[0],) + tuple(shape[1:]))
            by_dtype.setdefault(np.dtype(vals.dtype), []).append(
                (k, dense, shape, ctx))
        for dt, entries in by_dtype.items():
            flat = np.concatenate([d.reshape(-1) for _k, d, _s, _c in entries])
            total = dist.allreduce(flat)
            off = 0
            for k, d, shape, ctx in entries:
                agg = total[off:off + d.size].reshape(shape)
                off += d.size
                union = np.unique(np.concatenate(
                    [id_slices[(wrk, k)] for wrk in range(nworkers)]))
                union = union.astype(np.int64)
                _emit(k, agg[union], union, shape, ctx)

        if not sparse_keys:
            return
        widths = {}
        for k in sparse_keys:
            shape = rsp[k][3]
            widths[k] = int(np.prod(shape[1:])) if len(shape) > 1 else 1

        # values: one gather per dtype, padded to that dtype's max total
        dtypes = sorted({np.dtype(rsp[k][1].dtype) for k in sparse_keys},
                        key=str)
        gathered_vals = {}
        for dt in dtypes:
            dt_keys = [k for k in sparse_keys
                       if np.dtype(rsp[k][1].dtype) == dt]
            counts = np.stack(
                [nnz_all[:, kidx[k]] * widths[k] for k in dt_keys],
                axis=1)  # (W, K_dt)
            max_v = int(counts.sum(axis=1).max())
            buf = np.zeros((max(max_v, 1),), dt)
            my_flat = np.concatenate(
                [np.asarray(rsp[k][1]).reshape(-1) for k in dt_keys])
            buf[:my_flat.size] = my_flat
            gathered_vals[dt] = dist.allgather(buf)

        # reassemble per key; value offsets walk sparse_keys order per
        # dtype, matching the concatenation above
        val_offsets = {dt: np.zeros((nworkers,), np.int64) for dt in dtypes}
        for k in sparse_keys:
            _tag, vals, ids, shape, ctx = rsp[k]
            dt = np.dtype(vals.dtype)
            w_k = widths[k]
            id_parts, val_parts = [], []
            for wrk in range(nworkers):
                n = int(nnz_all[wrk, kidx[k]])
                vo = int(val_offsets[dt][wrk])
                if n:
                    id_parts.append(id_slices[(wrk, k)])
                    val_parts.append(
                        gathered_vals[dt][wrk, vo:vo + n * w_k]
                        .reshape((n,) + tuple(shape[1:])))
                val_offsets[dt][wrk] += n * w_k
            if not id_parts:
                id_parts = [np.zeros((0,), np.int64)]
                val_parts = [np.zeros((0,) + tuple(shape[1:]), vals.dtype)]
            _emit(k, np.concatenate(val_parts), np.concatenate(id_parts),
                  shape, ctx)

    def _accumulate_rsp(self, k, agg):
        """store[k] += row-sparse agg (server DataHandleRowSparse add)."""
        from .ndarray.sparse import RowSparseNDArray
        from .ndarray import sparse as nd_sparse

        store = self._store[k]
        if isinstance(store, RowSparseNDArray):
            self._store[k] = nd_sparse.add(store, agg)
            return
        ids = agg.indices._data().astype("int32")
        new = store._data().at[ids].add(agg.data._data())
        store._rebind(new)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        self._flush()
        super().pull(key, out=out, priority=priority, ignore_sparse=ignore_sparse)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        self._flush()
        return super().row_sparse_pull(key, out=out, priority=priority, row_ids=row_ids)

    def barrier(self):
        from . import dist

        self._flush()
        nd.waitall()
        dist.barrier()


def create(name="local"):
    """Create a KVStore (ref: kvstore.cc:38-66 factory)."""
    if not isinstance(name, str):
        raise MXNetError("name must be a string")
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "device", "local_allreduce_device", "nccl"):
        return KVStore(name)
    if name in ("tpu", "dist_sync_tpu"):
        return TPUKVStore(name)
    if name.startswith("dist"):
        if name == "dist_async":
            uri = os.environ.get("MXNET_PS_SERVER_URI")
            if uri:
                # true server-side-optimizer tier (ref dist_async
                # contract): pushes apply on arrival at the server
                from .kvstore_server import ServerKVStore

                return ServerKVStore(uri, name)
            from . import tracker

            if tracker.tracker_env_spec() is not None:
                # scheduler topology (tools/launch.py -n W -s S): the
                # tracker published every server's URI at rendezvous —
                # no hand-set MXNET_PS_SERVER_URI needed
                from .kvstore_server import ServerKVStore

                try:
                    uris = tracker.discover_server_uris()
                except tracker.TrackerError as e:
                    raise MXNetError(
                        "dist_async: scheduler rendezvous failed: %s" % e)
                return ServerKVStore(uris, name,
                                     tracker_client=tracker.worker_client())
        else:
            from . import tracker

            if tracker.tracker_env_spec() is not None:
                # scheduler topology, but this mode's sync path is the
                # jax collective whose rendezvous env the topology
                # replaces — each worker would silently train its own
                # unsynchronized model copy (loss still decreases, so
                # nothing would ever surface it)
                raise MXNetError(
                    "kvstore %r has no synchronization path under the "
                    "scheduler topology (launch.py -s > 0): workers "
                    "would train unsynchronized. Use --kv-store "
                    "dist_async (parameter-server tier) or launch with "
                    "-s 0 for the serverless collective path" % name)
        return DistKVStore(name)
    raise MXNetError("unknown kvstore type %r" % name)
