"""Weight initializers.

Reference counterpart: ``python/mxnet/initializer.py`` (726 LoC): registry,
InitDesc pattern matching (bias→zero, gamma→one, …), Uniform/Normal/Xavier/
MSRAPrelu/Orthogonal/Bilinear/LSTMBias/One/Zero/Constant/Mixed.
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import MXNetError
from .ndarray import ndarray as nd

def _rng():
    """Module-owned RandomState: seeded by mx.random.seed (reference
    parity — initializers follow the engine RNG), leaving the user's
    global numpy RNG untouched."""
    from . import random as _random

    return _random.initializer_rng()


_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


class InitDesc(str):
    """Name + attrs descriptor handed to initializers (ref: initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer with the reference's name-pattern dispatch."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init = desc.attrs.get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # default leaf rules
    def _init_bias(self, desc, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_gamma(self, desc, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_beta(self, desc, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_zero(self, desc, arr):
        self._set(arr, np.zeros(arr.shape))

    def _init_one(self, desc, arr):
        self._set(arr, np.ones(arr.shape))

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    @staticmethod
    def _set(arr, value):
        arr[:] = nd.array(np.asarray(value, dtype=np.float32), ctx=arr.ctx, dtype=arr.dtype)


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        self._set(arr, np.zeros(arr.shape))


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        self._set(arr, np.ones(arr.shape))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        self._set(arr, np.full(arr.shape, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        self._set(arr, _rng().uniform(-self.scale, self.scale, arr.shape))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        self._set(arr, _rng().normal(0, self.sigma, arr.shape))


@register
class Xavier(Initializer):
    """ref: initializer.py Xavier — gaussian/uniform over avg/in/out fans."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError("Xavier requires ndim >= 2: %r %s" % (desc, (shape,)))
        if len(shape) > 2:
            for s in shape[2:]:
                hw_scale *= s
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[
            self.factor_type
        ]
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, _rng().uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, _rng().normal(0, scale, shape))
        else:
            raise MXNetError("unknown rnd_type %r" % self.rnd_type)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope**2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _rng().uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _rng().normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        self._set(arr, self.scale * q.reshape(arr.shape))


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (ref: initializer.py Bilinear)."""

    def _init_weight(self, desc, arr):
        weight = np.zeros(arr.shape, dtype=np.float32)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (ref: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden : 2 * num_hidden] = self.forget_bias
        self._set(arr, b)


@register
class FusedRNN(Initializer):
    """Initialize flat fused-RNN parameter vectors (ref: initializer.py FusedRNN)."""

    def __init__(self, init=None, num_hidden=0, num_layers=0, mode="lstm",
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = create(klass, **kwargs)
        super().__init__(init=init.dumps() if init else None, num_hidden=num_hidden,
                         num_layers=num_layers, mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init or Uniform(0.07)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        # initialize whole flat vector with the inner init, then set LSTM
        # forget-gate biases; layout matches ops/nn.py rnn() unpacking.
        flat = _rng().uniform(-0.07, 0.07, arr.shape).astype(np.float32)
        H = self._num_hidden
        L = self._num_layers
        D = 2 if self._bidirectional else 1
        ngates = {"lstm": 4, "gru": 3, "rnn_relu": 1, "rnn_tanh": 1}[self._mode]
        if self._mode == "lstm":
            total = arr.shape[0]
            bias_total = L * D * 2 * ngates * H
            off = total - bias_total
            for _ in range(L * D):
                flat[off + H : off + 2 * H] = self._forget_bias  # b_ih forget
                off += ngates * H
                off += ngates * H  # skip b_hh
        self._set(arr, flat)


_INIT_REGISTRY["fusedrnn"] = FusedRNN


class Mixed:
    """Pattern→initializer dispatch (ref: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must have same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.search(str(name)):
                init(name, arr)
                return
        raise MXNetError("no initializer pattern matches %r" % str(name))


class Load:
    """Init from saved dict with fallback (ref: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray.utils import load as nd_load

            param = nd_load(param)
        self.param = {
            (k[4:] if k.startswith("arg:") or k.startswith("aux:") else k): v
            for k, v in param.items()
        }
        self.default_init = default_init

    def __call__(self, name, arr):
        name = str(name)
        if name in self.param:
            self.param[name].copyto(arr)
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise MXNetError("cannot init %r: not found and no default" % name)


# registry aliases matching the reference's registered names
_INIT_REGISTRY["zeros"] = Zero
_INIT_REGISTRY["ones"] = One
_INIT_REGISTRY["xavier"] = Xavier
_INIT_REGISTRY["msra_prelu"] = MSRAPrelu
_INIT_REGISTRY["lstmbias"] = LSTMBias


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    klass = _INIT_REGISTRY.get(name.lower())
    if klass is None:
        raise MXNetError("unknown initializer %r" % name)
    return klass(**kwargs)


# `mx.init.*` namespace shim
class init:
    Zero = Zero
    One = One
    Constant = Constant
    Uniform = Uniform
    Normal = Normal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Orthogonal = Orthogonal
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    FusedRNN = FusedRNN
    Mixed = Mixed
    Load = Load
    Initializer = Initializer
    InitDesc = InitDesc
