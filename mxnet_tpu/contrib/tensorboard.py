"""TensorBoard metric-logging callback (ref:
python/mxnet/contrib/tensorboard.py LogMetricsCallback).

The event-file writer is pluggable: tensorboardX / torch.utils.
tensorboard when available, else a built-in minimal writer that emits
genuine TF-format event files (record framing + scalar summary protos
hand-encoded — no TF dependency), so ``tensorboard --logdir`` works in
this image too.
"""
from __future__ import annotations

import os
import struct
import time


def _masked_crc32c(data: bytes) -> int:
    """CRC32C with the TFRecord masking (the event-file framing checksum)."""
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


_CRC_TABLE = []


def _crc32c(buf: bytes) -> int:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    crc = 0xFFFFFFFF
    for b in buf:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _varint(n: int) -> bytes:
    # two's-complement 64-bit encode: negative steps (common sentinel -1)
    # must terminate, matching protobuf int64 varint semantics
    n &= (1 << 64) - 1
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _proto_field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _scalar_event(tag: str, value: float, step: int, wall: float) -> bytes:
    """Hand-encoded Event{wall_time, step, summary{value{tag, simple_value}}}."""
    tag_b = tag.encode()
    sv = _proto_field(1, 2) + _varint(len(tag_b)) + tag_b
    sv += _proto_field(2, 5) + struct.pack("<f", float(value))
    summary_value = _proto_field(1, 2) + _varint(len(sv)) + sv
    event = _proto_field(1, 1) + struct.pack("<d", wall)
    event += _proto_field(2, 0) + _varint(int(step))
    event += _proto_field(5, 2) + _varint(len(summary_value)) + summary_value
    return event


class _MiniEventWriter:
    """Minimal TF event-file writer (record framing per TFRecord spec)."""

    _seq = 0

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        # timestamp alone collides when two writers start within a second
        # (train+eval callbacks on one logdir): disambiguate by pid+seq
        _MiniEventWriter._seq += 1
        fname = "events.out.tfevents.%d.%d.%d.mxtpu" % (
            int(time.time()), os.getpid(), _MiniEventWriter._seq)
        self._f = open(os.path.join(logdir, fname), "ab")
        self._write_event(_proto_field(1, 1) + struct.pack("<d", time.time()))

    def _write_event(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc32c(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc32c(payload)))

    def add_scalar(self, tag, value, global_step=0):
        self._write_event(_scalar_event(tag, value, global_step, time.time()))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


def _make_writer(logdir):
    try:
        from torch.utils.tensorboard import SummaryWriter

        return SummaryWriter(logdir)
    except Exception:
        pass
    try:
        from tensorboardX import SummaryWriter

        return SummaryWriter(logdir)
    except Exception:
        pass
    return _MiniEventWriter(logdir)


class LogMetricsCallback:
    """Batch-end callback writing eval metrics as TensorBoard scalars
    (ref: contrib/tensorboard.py LogMetricsCallback.__call__)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = _make_writer(logging_dir)

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
        self.summary_writer.flush()
