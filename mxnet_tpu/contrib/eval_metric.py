"""Detection mAP metrics: MApMetric (area-under-PR) and VOC07MApMetric
(11-point interpolation).

Reference counterpart: ``example/ssd/evaluate/eval_metric.py``
(MApMetric/VOC07MApMetric) — the evaluation half of the SSD config
whose BASELINE target is 77.8 VOC07 mAP. Same label/pred contract:

- labels: (B, N, 5) or (B, N, 6) ground truths per image,
  rows ``[cls, xmin, ymin, xmax, ymax, (difficult)]``; cls < 0 = pad.
- preds[pred_idx]: (B, M, 6) detections (MultiBoxDetection output),
  rows ``[cls, score, xmin, ymin, xmax, ymax]``; cls < 0 = suppressed.

Implementation is vectorized per (image, class): one IoU matrix,
greedy assignment in score order, per-class score/TP buffers folded
into AP at ``get()`` time.
"""
from __future__ import annotations

import numpy as np

from ..metric import EvalMetric, register


def _iou_matrix(dets, gts):
    """IoU of every det box against every gt box: (D, G)."""
    lt = np.maximum(dets[:, None, :2], gts[None, :, :2])
    rb = np.minimum(dets[:, None, 2:4], gts[None, :, 2:4])
    wh = np.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_d = np.prod(np.clip(dets[:, 2:4] - dets[:, :2], 0.0, None), axis=1)
    area_g = np.prod(np.clip(gts[:, 2:4] - gts[:, :2], 0.0, None), axis=1)
    union = area_d[:, None] + area_g[None, :] - inter
    with np.errstate(divide="ignore", invalid="ignore"):
        iou = np.where(union > 1e-12, inter / union, 0.0)
    return iou


@register("m_ap", "mAP")
class MApMetric(EvalMetric):
    """Mean average precision over detection classes.

    Parameters mirror the reference: ``ovp_thresh`` IoU for a true
    positive, ``use_difficult`` counts difficult ground truths,
    ``class_names`` reports per-class AP rows plus the mean,
    ``pred_idx`` selects the detection output.
    """

    def __init__(self, ovp_thresh=0.5, use_difficult=False,
                 class_names=None, pred_idx=0, name="mAP", **kwargs):
        self.ovp_thresh = float(ovp_thresh)
        self.use_difficult = bool(use_difficult)
        self.class_names = list(class_names) if class_names else None
        self.pred_idx = int(pred_idx)
        super().__init__(name, **kwargs)

    def reset(self):
        # per-class: list of (score, is_tp) rows + total gt count
        self._scores = {}
        self._gt_counts = {}

    def _to_np(self, x):
        return x.asnumpy() if hasattr(x, "asnumpy") else np.asarray(x)

    def update(self, labels, preds):
        labels = self._to_np(labels[0])
        preds = self._to_np(preds[self.pred_idx])
        for img_label, img_pred in zip(labels, preds):
            self._update_image(np.asarray(img_label, np.float64),
                               np.asarray(img_pred, np.float64))

    def _update_image(self, gts, dets):
        gts = gts[gts[:, 0] >= 0]
        if len(gts) == 0:
            # ref parity: images with no (non-pad) ground truth are
            # skipped entirely — their detections are NOT false
            # positives (eval_metric.py "if np.sum(label[:, 0] >= 0)
            # < 1: continue"); counting them would depress mAP vs the
            # 77.8 VOC07 baseline on datasets with empty images
            return
        dets = dets[dets[:, 0] >= 0]
        difficult = (gts[:, 5] > 0 if gts.shape[1] >= 6 and
                     not self.use_difficult
                     else np.zeros(len(gts), dtype=bool))
        classes = set(gts[:, 0].astype(int)) | set(dets[:, 0].astype(int))
        for cid in classes:
            g = gts[gts[:, 0].astype(int) == cid]
            g_diff = difficult[gts[:, 0].astype(int) == cid]
            d = dets[dets[:, 0].astype(int) == cid]
            d = d[np.argsort(-d[:, 1])]  # score descending
            n_gt = int((~g_diff).sum())
            rows = []
            if len(d):
                if len(g):
                    iou = _iou_matrix(d[:, 2:6], g[:, 1:5])
                    taken = np.zeros(len(g), dtype=bool)
                    for j in range(len(d)):
                        best = int(np.argmax(iou[j]))
                        if iou[j, best] > self.ovp_thresh:
                            if g_diff[best]:
                                continue  # matched difficult: uncounted
                            if not taken[best]:
                                taken[best] = True
                                rows.append((d[j, 1], 1))
                            else:
                                rows.append((d[j, 1], 0))  # duplicate: fp
                        else:
                            rows.append((d[j, 1], 0))
                else:
                    rows = [(s, 0) for s in d[:, 1]]
            self._scores.setdefault(cid, []).extend(rows)
            self._gt_counts[cid] = self._gt_counts.get(cid, 0) + n_gt

    def _class_ap(self, cid):
        rows = np.asarray(self._scores.get(cid, ()), np.float64)
        n_gt = self._gt_counts.get(cid, 0)
        if rows.size == 0:
            return 0.0 if n_gt > 0 else float("nan")
        order = np.argsort(-rows[:, 0])
        tp = np.cumsum(rows[order, 1])
        fp = np.cumsum(1.0 - rows[order, 1])
        recall = tp / n_gt if n_gt > 0 else tp * 0.0
        precision = tp / np.maximum(tp + fp, 1e-12)
        return self._average_precision(recall, precision)

    @staticmethod
    def _average_precision(recall, precision):
        """Area under the monotone precision envelope."""
        r = np.concatenate(([0.0], recall, [1.0]))
        p = np.concatenate(([0.0], precision, [0.0]))
        p = np.maximum.accumulate(p[::-1])[::-1]
        steps = np.nonzero(r[1:] != r[:-1])[0]
        return float(np.sum((r[steps + 1] - r[steps]) * p[steps + 1]))

    def get(self):
        cids = sorted(set(self._scores) | set(self._gt_counts))
        aps = {cid: self._class_ap(cid) for cid in cids}
        valid = [v for v in aps.values() if not np.isnan(v)]
        mean = float(np.mean(valid)) if valid else float("nan")
        if self.class_names is None:
            return (self.name, mean)
        names = list(self.class_names) + [self.name]
        values = [aps.get(i, float("nan"))
                  for i in range(len(self.class_names))] + [mean]
        return (names, values)


@register("voc07_m_ap", "VOC07MApMetric")
class VOC07MApMetric(MApMetric):
    """PASCAL VOC 2007 11-point interpolated AP."""

    @staticmethod
    def _average_precision(recall, precision):
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            mask = recall >= t
            ap += (float(np.max(precision[mask])) if mask.any() else 0.0) / 11.0
        return ap
