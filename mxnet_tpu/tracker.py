"""Tracker: the scheduler-rendezvous process topology for dist training.

Reference counterpart: the dmlc-core tracker behind ``tools/launch.py``
(tools/launch.py:33-46) plus the ps-lite scheduler node (SURVEY §2.4,
kvstore.h:267-311): one scheduler process accepts registrations from
``DMLC_ROLE``-tagged servers and workers, assigns ranks per role, and
publishes the server endpoints to every worker so
``kvstore.create('dist_async')`` discovers its parameter server with no
hand-set ``MXNET_PS_SERVER_URI``.

Beyond rendezvous, the scheduler is the robustness layer of the
topology:

- **heartbeats + dead-node detection** — clients beat on a dedicated
  connection; a node whose beats stop (or whose connections drop) is
  marked dead, and ``num_dead_node`` reports the count (ref:
  ps-lite heartbeats behind kvstore.h:330-340 get_num_dead_node);
- **barrier recovery** — a tracker barrier whose peer dies is *aborted*
  with an error to every survivor instead of spinning forever;
- **bounded-backoff connect** — clients retry the scheduler (and
  workers retry their servers) with exponential backoff up to a
  deadline, so process start order does not matter;
- **graceful shutdown fan-out** — when every worker reports ``done``
  (or is dead), the scheduler sends ``stop`` to each registered server
  and exits, so ``tools/launch.py`` jobs terminate cleanly.

This module is deliberately **stdlib-only** (no jax/numpy): the
scheduler process imports in milliseconds and the module is importable
from anywhere in the package without cycles.

Protocol: 4-byte big-endian length + restricted-pickle payload
``(op, payload_dict)`` with replies ``("ok", payload)`` /
``("err", text)`` — the same plain-data-only wire discipline as
``kvstore_server`` (no global lookups ever unpickled). In-cluster
protocol, no auth; do not expose the port beyond the job.
"""
from __future__ import annotations

import io
import os
import pickle
import socket
import struct
import sys
import threading
import time


DEFAULT_HEARTBEAT_TIMEOUT = 30.0   # seconds without a beat => dead
DEFAULT_HEARTBEAT_INTERVAL = 2.0   # client beat period
DEFAULT_BARRIER_TIMEOUT = 120.0    # overall tracker-barrier bound


class TrackerError(RuntimeError):
    """Tracker-layer failure (connect exhausted, barrier broken, ...)."""


# ---------------------------------------------------------------------------
# wire helpers (restricted pickle: plain data only)
# ---------------------------------------------------------------------------
class _SafeUnpickler(pickle.Unpickler):
    """Shared by the tracker AND kvstore_server protocols (one framing,
    one hardening surface): refuse every global lookup."""

    def find_class(self, module, name):
        raise pickle.UnpicklingError(
            "this protocol carries data only (%s.%s refused)"
            % (module, name))


def _pack(obj):
    return pickle.dumps(obj, protocol=4)


def _unpack(raw):
    return _SafeUnpickler(io.BytesIO(raw)).load()


def _send_msg(sock, obj):
    raw = _pack(obj)
    sock.sendall(struct.pack(">I", len(raw)) + raw)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tracker: peer closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    return _unpack(_recv_exact(sock, n))


def connect_with_backoff(uri, deadline=30.0, base_delay=0.05, max_delay=2.0):
    """TCP connect with bounded exponential backoff (the topology's
    answer to arbitrary process start order: a worker may come up before
    its scheduler or server is listening). Raises TrackerError once the
    deadline is exhausted."""
    host, port = uri.rsplit(":", 1)
    stop_at = time.monotonic() + float(deadline)
    delay = base_delay
    last_err = None
    while True:
        remaining = stop_at - time.monotonic()
        if remaining <= 0:
            raise TrackerError(
                "could not connect to %s within %.0fs (last error: %s)"
                % (uri, deadline, last_err))
        try:
            return socket.create_connection(
                (host, int(port)), timeout=min(max(remaining, 0.1), 10.0))
        except OSError as e:
            last_err = e
            time.sleep(min(delay, max(stop_at - time.monotonic(), 0)))
            delay = min(delay * 2, max_delay)


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------
class _Node:
    __slots__ = ("node_id", "role", "rank", "addr", "last_beat", "alive",
                 "done")

    def __init__(self, node_id, role, rank, addr):
        self.node_id = node_id
        self.role = role
        self.rank = rank
        self.addr = addr
        self.last_beat = time.monotonic()
        self.alive = True
        self.done = False


class Tracker:
    """The scheduler process: registration, rank assignment, server-URI
    publication, heartbeats, barriers with dead-peer recovery, shutdown
    fan-out."""

    def __init__(self, host="127.0.0.1", port=0, num_workers=1,
                 num_servers=0, heartbeat_timeout=DEFAULT_HEARTBEAT_TIMEOUT,
                 barrier_timeout=DEFAULT_BARRIER_TIMEOUT):
        self._num_workers = int(num_workers)
        self._num_servers = int(num_servers)
        self._heartbeat_timeout = float(heartbeat_timeout)
        self._barrier_timeout = float(barrier_timeout)
        self._cv = threading.Condition()
        self._nodes = {}            # node_id -> _Node
        self._next_id = 0
        self._next_rank = {"worker": 0, "server": 0}
        self._barriers = {}         # name -> {"gen": int, "arrived": set}
        self._barrier_errors = {}   # (name, gen) -> message
        self._stop = threading.Event()
        self._fanned_out = False
        self._conns = set()         # live client connections
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.addr = "%s:%d" % self._sock.getsockname()[:2]

    # -- state helpers (lock held) -------------------------------------------
    def _num_dead_locked(self):
        return sum(1 for n in self._nodes.values()
                   if not n.alive and not n.done)

    def _servers_locked(self):
        return sorted((n for n in self._nodes.values()
                       if n.role == "server"), key=lambda n: n.rank)

    def _abort_barrier_locked(self, name, msg):
        b = self._barriers.get(name)
        if b is None or not b["arrived"]:
            return
        self._barrier_errors[(name, b["gen"])] = msg
        # prune: keep only the newest few abort records
        while len(self._barrier_errors) > 32:
            self._barrier_errors.pop(next(iter(self._barrier_errors)))
        b["gen"] += 1
        b["arrived"] = set()
        self._cv.notify_all()

    def _mark_dead_locked(self, node_id, why):
        node = self._nodes.get(node_id)
        if node is None or node.done or not node.alive:
            return
        node.alive = False
        for name in list(self._barriers):
            self._abort_barrier_locked(
                name, "barrier %r broken: %s %d (rank %d) died (%s)"
                % (name, node.role, node_id, node.rank, why))
        self._cv.notify_all()
        self._maybe_finish_locked()

    def _maybe_finish_locked(self):
        """All expected workers done-or-dead => shutdown fan-out."""
        workers = [n for n in self._nodes.values() if n.role == "worker"]
        if len(workers) < self._num_workers or self._fanned_out:
            return
        if all(n.done or not n.alive for n in workers):
            self._fanned_out = True
            servers = [n.addr for n in self._servers_locked() if n.addr]
            threading.Thread(target=self._fan_out_stop, args=(servers,),
                             daemon=True).start()

    def _fan_out_stop(self, server_addrs):
        """Send the kvstore_server protocol 'stop' to every server, then
        stop the tracker itself (graceful job teardown)."""
        for addr in server_addrs:
            try:
                s = connect_with_backoff(addr, deadline=5.0)
                try:
                    # kvstore_server wire: (op, key, meta, wire) 4-tuple
                    _send_msg(s, ("stop", None, None, None))
                    s.settimeout(5.0)
                    _recv_msg(s)
                finally:
                    s.close()
            except (TrackerError, OSError, ConnectionError):
                pass  # server already gone
        self.shutdown()

    # -- op handlers ---------------------------------------------------------
    def _op_register(self, conn_nodes, p):
        role = p.get("role")
        if role not in ("worker", "server"):
            raise ValueError("register: bad role %r" % (role,))
        with self._cv:
            limit = (self._num_workers if role == "worker"
                     else self._num_servers)
            rank = self._next_rank[role]
            if rank >= limit:
                raise ValueError(
                    "register: all %d %s ranks already assigned"
                    % (limit, role))
            self._next_rank[role] += 1
            nid = self._next_id
            self._next_id += 1
            self._nodes[nid] = _Node(nid, role, rank, p.get("addr"))
            conn_nodes.add(nid)
            self._cv.notify_all()
        return {"node_id": nid, "rank": rank,
                "num_workers": self._num_workers,
                "num_servers": self._num_servers}

    def _op_get_servers(self, p):
        """Block until every expected server registered; return their
        URIs in rank order."""
        timeout = float(p.get("timeout", 60.0))
        deadline = time.monotonic() + timeout
        with self._cv:
            while not self._stop.is_set():
                servers = self._servers_locked()
                if len(servers) >= self._num_servers:
                    return [n.addr for n in servers]
                dead = [n for n in servers if not n.alive]
                if dead:
                    raise TrackerError(
                        "get_servers: server rank %d died during "
                        "rendezvous" % dead[0].rank)
                if time.monotonic() >= deadline:
                    raise TrackerError(
                        "get_servers: %d of %d servers registered within "
                        "%.0fs" % (len(servers), self._num_servers, timeout))
                self._cv.wait(timeout=0.2)
            raise TrackerError("get_servers: tracker stopped")

    def _op_heartbeat(self, conn_nodes, p):
        nid = p.get("node_id")
        with self._cv:
            node = self._nodes.get(nid)
            if node is None:
                raise ValueError("heartbeat: unknown node %r" % (nid,))
            conn_nodes.add(nid)
            node.last_beat = time.monotonic()
            return {"num_dead": self._num_dead_locked()}

    def _op_barrier(self, p):
        """All expected workers must arrive; a dead peer aborts the
        round with an error to every waiter (instead of the reference's
        infinite spin), and an overall timeout bounds the wait."""
        nid = p.get("node_id")
        name = p.get("name", "")
        timeout = float(p.get("timeout") or self._barrier_timeout)
        deadline = time.monotonic() + timeout
        with self._cv:
            b = self._barriers.setdefault(name, {"gen": 0, "arrived": set()})
            gen = b["gen"]
            b["arrived"].add(nid)
            if len(b["arrived"]) >= self._num_workers:
                b["gen"] += 1
                b["arrived"] = set()
                self._cv.notify_all()
                return None
            while b["gen"] == gen and not self._stop.is_set():
                if time.monotonic() >= deadline:
                    msg = ("barrier %r timed out after %.0fs (%d of %d "
                           "workers arrived)"
                           % (name, timeout, len(b["arrived"]),
                              self._num_workers))
                    self._abort_barrier_locked(name, msg)
                    raise TrackerError(msg)
                self._cv.wait(timeout=0.2)
            err = self._barrier_errors.get((name, gen))
            if err is not None:
                raise TrackerError(err)
            if self._stop.is_set() and b["gen"] == gen:
                raise TrackerError("barrier %r: tracker stopped" % (name,))
            return None

    def _op_done(self, p):
        nid = p.get("node_id")
        with self._cv:
            node = self._nodes.get(nid)
            if node is not None:
                node.done = True
            self._maybe_finish_locked()
        return None

    def _op_num_dead(self):
        with self._cv:
            return self._num_dead_locked()

    def _op_nodes(self):
        """Topology snapshot (debugging / tests)."""
        with self._cv:
            return [{"node_id": n.node_id, "role": n.role, "rank": n.rank,
                     "addr": n.addr, "alive": n.alive, "done": n.done}
                    for n in self._nodes.values()]

    def _dispatch(self, conn_nodes, op, p):
        if op == "register":
            return self._op_register(conn_nodes, p)
        if op == "get_servers":
            return self._op_get_servers(p)
        if op == "heartbeat":
            return self._op_heartbeat(conn_nodes, p)
        if op == "barrier":
            return self._op_barrier(p)
        if op == "done":
            return self._op_done(p)
        if op == "num_dead":
            return self._op_num_dead()
        if op == "nodes":
            return self._op_nodes()
        raise ValueError("unknown op %r" % (op,))

    # -- connection loop -----------------------------------------------------
    def _handle(self, conn):
        conn_nodes = set()  # node_ids bound to this connection
        try:
            while not self._stop.is_set():
                op, p = _recv_msg(conn)
                if op == "stop":
                    _send_msg(conn, ("ok", None))
                    self.shutdown()
                    return
                try:
                    payload = self._dispatch(conn_nodes, op, p or {})
                except Exception as e:
                    try:
                        _send_msg(conn, ("err", "%s: %s"
                                         % (type(e).__name__, e)))
                    except OSError:
                        raise ConnectionError("reply failed")
                    continue
                _send_msg(conn, ("ok", payload))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            self._conns.discard(conn)
            conn.close()
            # a dropped connection kills every node bound to it (fast
            # dead detection for SIGKILLed processes; graceful exits
            # sent "done" first, which _mark_dead respects)
            with self._cv:
                for nid in conn_nodes:
                    self._mark_dead_locked(nid, "connection dropped")

    def _monitor(self):
        """Heartbeat scan: nodes whose beats stopped are dead."""
        tick = max(self._heartbeat_timeout / 4.0, 0.2)
        while not self._stop.wait(tick):
            now = time.monotonic()
            with self._cv:
                for n in list(self._nodes.values()):
                    if (n.alive and not n.done
                            and now - n.last_beat > self._heartbeat_timeout):
                        self._mark_dead_locked(n.node_id, "heartbeat lost")

    def serve_forever(self):
        self._sock.settimeout(0.5)
        threading.Thread(target=self._monitor, daemon=True).start()
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conns.add(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=2)

    def serve_in_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        # closing live conns unblocks handler threads parked in recv so
        # serve_forever's joins return immediately instead of timing out
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class TrackerClient:
    """One node's connection to the scheduler: registers on construction
    (rank assignment), beats on a dedicated second connection so long
    barrier waits never starve the heartbeat, and exposes the
    rendezvous/barrier/failure-count surface."""

    def __init__(self, uri, role, addr=None,
                 connect_deadline=30.0,
                 heartbeat_interval=None):
        self._uri = uri
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._done_sent = False
        self._sock = connect_with_backoff(uri, deadline=connect_deadline)
        info = self._rpc("register", {"role": role, "addr": addr})
        self.node_id = info["node_id"]
        self.rank = info["rank"]
        self.num_workers = info["num_workers"]
        self.num_servers = info["num_servers"]
        self.role = role
        # heartbeats: dedicated connection + thread
        if heartbeat_interval is None:
            heartbeat_interval = float(os.environ.get(
                "MXNET_TRACKER_HEARTBEAT_INTERVAL",
                str(DEFAULT_HEARTBEAT_INTERVAL)))
        self._hb_sock = connect_with_backoff(uri, deadline=connect_deadline)
        self._hb_thread = threading.Thread(
            target=self._beat, args=(float(heartbeat_interval),),
            daemon=True)
        self._hb_thread.start()

    def _rpc(self, op, payload=None, timeout=60.0, sock=None, lock=None):
        sock = sock or self._sock
        try:
            with (lock or self._lock):
                sock.settimeout(timeout)
                _send_msg(sock, (op, payload or {}))
                status, reply = _recv_msg(sock)
        except (socket.timeout, OSError, ConnectionError) as e:
            # a timed-out request's late reply would otherwise be read
            # as the NEXT op's reply — invalidate the connection and
            # raise the domain error kvstore.create() knows to catch
            try:
                sock.close()
            except OSError:
                pass
            raise TrackerError(
                "tracker rpc %r to %s failed (%s: %s); connection closed"
                % (op, self._uri, type(e).__name__, e))
        if status != "ok":
            raise TrackerError("tracker: %s" % (reply,))
        return reply

    def _beat(self, interval):
        hb_lock = threading.Lock()
        while not self._closed.wait(interval):
            try:
                self._rpc("heartbeat", {"node_id": self.node_id},
                          timeout=10.0, sock=self._hb_sock, lock=hb_lock)
            except (TrackerError, OSError, ConnectionError):
                return  # tracker gone; stop beating

    # -- surface -------------------------------------------------------------
    def get_server_uris(self, timeout=60.0):
        """Block until every server registered; URIs in rank order."""
        return self._rpc("get_servers", {"timeout": timeout},
                         timeout=timeout + 10.0)

    def barrier(self, name="", timeout=None):
        """Tracker barrier across all workers. Raises TrackerError on a
        dead peer or on the overall timeout — never spins forever."""
        timeout = float(timeout if timeout is not None
                        else os.environ.get("MXNET_TRACKER_BARRIER_TIMEOUT",
                                            str(DEFAULT_BARRIER_TIMEOUT)))
        self._rpc("barrier",
                  {"node_id": self.node_id, "name": name, "timeout": timeout},
                  timeout=timeout + 15.0)

    def num_dead_node(self):
        return int(self._rpc("num_dead"))

    def nodes(self):
        return self._rpc("nodes")

    def done(self):
        """Report graceful completion (idempotent; swallows a dead
        tracker — at-exit teardown must never raise)."""
        if self._done_sent:
            return
        self._done_sent = True
        try:
            self._rpc("done", {"node_id": self.node_id}, timeout=10.0)
        except (TrackerError, OSError, ConnectionError):
            pass

    def close(self):
        self._closed.set()
        for s in (self._sock, self._hb_sock):
            try:
                s.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# env contract + worker-side discovery singleton
# ---------------------------------------------------------------------------
def tracker_env_spec():
    """(scheduler_uri, num_workers, num_servers) from the DMLC env, or
    None when no scheduler topology is configured. The topology exists
    exactly when DMLC_PS_ROOT_URI/PORT name the scheduler AND
    DMLC_NUM_SERVER asks for parameter servers."""
    host = os.environ.get("DMLC_PS_ROOT_URI")
    port = os.environ.get("DMLC_PS_ROOT_PORT")
    try:
        num_servers = int(os.environ.get("DMLC_NUM_SERVER", "0") or 0)
    except ValueError:
        return None
    if not host or not port or num_servers <= 0:
        return None
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1") or 1)
    return ("%s:%s" % (host, port), num_workers, num_servers)


_WORKER_CLIENT = None
_WORKER_CLIENT_LOCK = threading.Lock()


def worker_client():
    """This process's TrackerClient (role=worker), created on first use
    from the env contract; None when no scheduler topology is
    configured. Registers an atexit hook that reports ``done`` so the
    scheduler can fan out shutdown to the servers."""
    global _WORKER_CLIENT
    with _WORKER_CLIENT_LOCK:
        if _WORKER_CLIENT is not None:
            return _WORKER_CLIENT
        spec = tracker_env_spec()
        if spec is None:
            return None
        uri, _nw, _ns = spec
        client = TrackerClient(uri, "worker")
        import atexit

        atexit.register(lambda: (client.done(), client.close()))
        _WORKER_CLIENT = client
        return client


def discover_server_uris(timeout=60.0):
    """Worker-side rendezvous: register with the scheduler and block
    until every parameter server has published its URI. None when no
    scheduler topology is configured in the env."""
    client = worker_client()
    if client is None:
        return None
    return client.get_server_uris(timeout=timeout)


# ---------------------------------------------------------------------------
# scheduler entry point (DMLC_ROLE=scheduler)
# ---------------------------------------------------------------------------
def main():
    host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "0"))
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1") or 1)
    num_servers = int(os.environ.get("DMLC_NUM_SERVER", "0") or 0)
    hb_timeout = float(os.environ.get("MXNET_TRACKER_HEARTBEAT_TIMEOUT",
                                      str(DEFAULT_HEARTBEAT_TIMEOUT)))
    # bind-anywhere: the advertised host may be this host's external
    # name; bind the wildcard so both loopback and external connects work
    bind_host = "" if host not in ("127.0.0.1", "localhost") else host
    tracker = Tracker(host=bind_host, port=port, num_workers=num_workers,
                      num_servers=num_servers,
                      heartbeat_timeout=hb_timeout)
    print("tracker listening on %s (workers=%d servers=%d)"
          % (tracker.addr, num_workers, num_servers), flush=True)
    tracker.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
