"""Tracker: the scheduler-rendezvous process topology for dist training.

Reference counterpart: the dmlc-core tracker behind ``tools/launch.py``
(tools/launch.py:33-46) plus the ps-lite scheduler node (SURVEY §2.4,
kvstore.h:267-311): one scheduler process accepts registrations from
``DMLC_ROLE``-tagged servers and workers, assigns ranks per role, and
publishes the server endpoints to every worker so
``kvstore.create('dist_async')`` discovers its parameter server with no
hand-set ``MXNET_PS_SERVER_URI``.

Beyond rendezvous, the scheduler is the robustness layer of the
topology:

- **heartbeats + dead-node detection** — clients beat on a dedicated
  connection; a node whose beats stop (or whose connections drop) is
  marked dead, and ``num_dead_node`` reports the count (ref:
  ps-lite heartbeats behind kvstore.h:330-340 get_num_dead_node);
- **barrier recovery** — a tracker barrier whose peer dies is *aborted*
  with an error to every survivor instead of spinning forever;
- **bounded-backoff connect** — clients retry the scheduler (and
  workers retry their servers) with exponential backoff up to a
  deadline, so process start order does not matter;
- **graceful shutdown fan-out** — when every worker reports ``done``
  (or is dead beyond recovery), the scheduler sends ``stop`` to each
  registered server and exits, so ``tools/launch.py`` jobs terminate
  cleanly;
- **elastic respawn (ISSUE 3)** — with ``MXNET_MAX_RESTARTS`` > 0 a
  dead node's rank is *recoverable*: ``tools/launch.py`` respawns the
  process with ``DMLC_RESTART_COUNT`` incremented, the replacement
  re-registers and takes over the dead rank (and, for servers, its
  published URI), pending barriers wait for the respawn instead of
  aborting, and the shutdown fan-out is deferred while a respawn is
  still possible. Every transition is logged as a structured
  ``[lifecycle]`` line on the scheduler's stdout — registered / dead /
  respawned / done / restored-from — so a post-mortem can reconstruct
  the job timeline from the launcher output alone.

This module is deliberately **stdlib-only** (no jax/numpy): the
scheduler process imports in milliseconds and the module is importable
from anywhere in the package without cycles.

Protocol: 4-byte big-endian length + restricted-pickle payload
``(op, payload_dict)`` with replies ``("ok", payload)`` /
``("err", text)`` — the same plain-data-only wire discipline as
``kvstore_server`` (no global lookups ever unpickled). In-cluster
protocol, no auth; do not expose the port beyond the job.
"""
from __future__ import annotations

import io
import os
import pickle
import socket
import struct
import sys
import threading
import time


DEFAULT_HEARTBEAT_TIMEOUT = 30.0   # seconds without a beat => dead
DEFAULT_HEARTBEAT_INTERVAL = 2.0   # client beat period
DEFAULT_BARRIER_TIMEOUT = 120.0    # overall tracker-barrier bound


class TrackerError(RuntimeError):
    """Tracker-layer failure (connect exhausted, barrier broken, ...)."""


# ---------------------------------------------------------------------------
# validated env knobs (ISSUE 3 satellite): a typo'd MXNET_TRACKER_*
# value must fail loudly at read time, not silently fall back to a
# default that masks the misconfiguration for the rest of the job
# ---------------------------------------------------------------------------
def env_positive_float(name, default):
    """float(os.environ[name]) requiring a finite value > 0; raises
    TrackerError on nonsense (non-numeric, 0, negative, inf/nan)."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return float(default)
    try:
        value = float(raw)
    except ValueError:
        raise TrackerError(
            "%s=%r is not a number (expected a positive duration in "
            "seconds)" % (name, raw))
    if not 0 < value < float("inf"):  # also rejects NaN
        raise TrackerError(
            "%s=%r must be a finite value > 0" % (name, raw))
    return value


def prune_barrier_names(barriers, errors, current, quiescent,
                        limit=64, min_idle=5.0):
    """Bound per-name barrier state (shared by Tracker and
    KVStoreServer — one definition, or the two would drift): evict
    quiescent names oldest-first once ``limit`` is exceeded, together
    with their abort records. Only entries idle for ``min_idle``
    seconds are touched: a just-aborted round's sleeping waiters (wait
    tick 0.2 s) must still find their abort record when they wake —
    evicting it would turn an aborted barrier into a silent success.
    Callers must hold their state lock and stamp ``s["ts"]`` on every
    touch."""
    if len(barriers) <= limit:
        return
    now = time.monotonic()
    stale = [n for n, s in barriers.items()
             if n != current and quiescent(s)
             and now - s.get("ts", now) >= min_idle]
    for name in stale[:len(barriers) - limit]:
        barriers.pop(name)
        for key in [k for k in errors if k[0] == name]:
            errors.pop(key)


def env_nonneg_int(name, default):
    """int(os.environ[name]) requiring >= 0; raises TrackerError on
    nonsense."""
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return int(default)
    try:
        value = int(raw)
    except ValueError:
        raise TrackerError(
            "%s=%r is not an integer (expected a count >= 0)"
            % (name, raw))
    if value < 0:
        raise TrackerError("%s=%r must be >= 0" % (name, raw))
    return value


# ---------------------------------------------------------------------------
# wire helpers (restricted pickle: plain data only)
# ---------------------------------------------------------------------------
class _SafeUnpickler(pickle.Unpickler):
    """Shared by the tracker AND kvstore_server protocols (one framing,
    one hardening surface): refuse every global lookup."""

    def find_class(self, module, name):
        raise pickle.UnpicklingError(
            "this protocol carries data only (%s.%s refused)"
            % (module, name))


#: frames whose 4-byte length prefix carries this bit are EXTENDED: the
#: pickled metadata is followed by out-of-band tensor buffers (pickle
#: protocol 5), so large arrays cross the wire as raw memoryviews with
#: no pickle-time copy on either side (ISSUE 4 zero-copy framing)
_OOB_FLAG = 0x80000000
#: bounds on the extended frame a peer may ask us to allocate — this is
#: an in-cluster protocol, but a corrupt length must not OOM the server
_OOB_MAX_BUFS = 4096
_OOB_MAX_BYTES = 1 << 33


def _pack(obj, buffer_callback=None):
    return pickle.dumps(obj, protocol=5, buffer_callback=buffer_callback)


def _unpack(raw, buffers=None):
    return _SafeUnpickler(io.BytesIO(raw), buffers=buffers).load()


def _send_msg(sock, obj):
    """Send one frame; returns the total bytes written (comms
    accounting). Objects containing ``pickle.PickleBuffer``-wrapped
    arrays are framed extended: metadata pickles WITHOUT the tensor
    bytes, then each buffer is written straight from the array's own
    memory (``sendall`` on a memoryview — no concatenation copy)."""
    bufs = []
    raw = _pack(obj, buffer_callback=bufs.append)
    if len(raw) >= _OOB_FLAG:
        # the flag bit halves the old 4 GiB inline ceiling: a frame
        # that large must fail loudly, not masquerade as extended
        raise ValueError(
            "wire frame metadata too large (%d bytes; limit %d)"
            % (len(raw), _OOB_FLAG - 1))
    if not bufs:
        payload = struct.pack(">I", len(raw)) + raw
        sock.sendall(payload)
        return len(payload)
    views = [pb.raw() for pb in bufs]
    header = struct.pack(">II", _OOB_FLAG | len(raw), len(views))
    header += b"".join(struct.pack(">Q", v.nbytes) for v in views)
    sock.sendall(header)
    sock.sendall(raw)
    for v in views:
        sock.sendall(v)
    return len(header) + len(raw) + sum(v.nbytes for v in views)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("tracker: peer closed")
        buf += chunk
    return buf


def _recv_into_exact(sock, buf):
    view = memoryview(buf)
    got = 0
    while got < len(buf):
        n = sock.recv_into(view[got:])
        if not n:
            raise ConnectionError("tracker: peer closed")
        got += n


def _recv_msg(sock, with_size=False):
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    if not n & _OOB_FLAG:
        obj = _unpack(_recv_exact(sock, n))
        return (obj, 4 + n) if with_size else obj
    raw_len = n & ~_OOB_FLAG
    (nbufs,) = struct.unpack(">I", _recv_exact(sock, 4))
    if nbufs > _OOB_MAX_BUFS:
        raise ConnectionError("bad frame: %d out-of-band buffers" % nbufs)
    lens = struct.unpack(">%dQ" % nbufs, _recv_exact(sock, 8 * nbufs))
    if sum(lens) > _OOB_MAX_BYTES:
        raise ConnectionError("bad frame: %d buffer bytes" % sum(lens))
    raw = _recv_exact(sock, raw_len)
    # buffers land in writable bytearrays the deserialized arrays view
    # directly — one kernel->user copy, nothing else
    bufs = []
    for ln in lens:
        buf = bytearray(ln)
        _recv_into_exact(sock, buf)
        bufs.append(buf)
    obj = _unpack(raw, buffers=bufs)
    if with_size:
        return obj, 4 + 4 + 8 * nbufs + raw_len + sum(lens)
    return obj


def connect_with_backoff(uri, deadline=30.0, base_delay=0.05, max_delay=2.0):
    """TCP connect with bounded exponential backoff (the topology's
    answer to arbitrary process start order: a worker may come up before
    its scheduler or server is listening). Raises TrackerError once the
    deadline is exhausted."""
    host, port = uri.rsplit(":", 1)
    stop_at = time.monotonic() + float(deadline)
    delay = base_delay
    last_err = None
    while True:
        remaining = stop_at - time.monotonic()
        if remaining <= 0:
            raise TrackerError(
                "could not connect to %s within %.0fs (last error: %s)"
                % (uri, deadline, last_err))
        try:
            return socket.create_connection(
                (host, int(port)), timeout=min(max(remaining, 0.1), 10.0))
        except OSError as e:
            last_err = e
            time.sleep(min(delay, max(stop_at - time.monotonic(), 0)))
            delay = min(delay * 2, max_delay)


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------
#: roles whose ranks come from the fixed worker/server slot pools sized
#: by DMLC_NUM_WORKER/DMLC_NUM_SERVER. Every OTHER role (the serving
#: fleet's ``replica``, a routing/admin client, ...) registers slot-free:
#: ranks are assigned from an unbounded per-role counter, the node never
#: consumes a worker/server slot, and its death never counts toward the
#: training job's ``num_dead_node`` parity (ISSUE 11 satellite).
SLOTTED_ROLES = ("worker", "server")


class _Node:
    __slots__ = ("node_id", "role", "rank", "addr", "last_beat", "alive",
                 "done", "replaced", "restart", "info")

    def __init__(self, node_id, role, rank, addr, restart=0):
        self.node_id = node_id
        self.role = role
        self.rank = rank
        self.addr = addr
        self.last_beat = time.monotonic()
        self.alive = True
        self.done = False
        self.replaced = False   # a respawn took over this node's rank
        self.restart = restart  # incarnation number (DMLC_RESTART_COUNT)
        self.info = {}          # published metadata (serving load gauge)


class Tracker:
    """The scheduler process: registration, rank assignment, server-URI
    publication, heartbeats, barriers with dead-peer recovery, elastic
    respawn bookkeeping, shutdown fan-out."""

    #: how long a respawning registration waits for the previous
    #: incarnation to be marked dead (its sockets close at process
    #: death, so conn-drop detection is near-immediate; this bound only
    #: matters for wedged-but-alive predecessors)
    TAKEOVER_WAIT = 10.0

    def __init__(self, host="127.0.0.1", port=0, num_workers=1,
                 num_servers=0, heartbeat_timeout=DEFAULT_HEARTBEAT_TIMEOUT,
                 barrier_timeout=DEFAULT_BARRIER_TIMEOUT,
                 max_restarts=None):
        self._num_workers = int(num_workers)
        self._num_servers = int(num_servers)
        self._heartbeat_timeout = float(heartbeat_timeout)
        self._barrier_timeout = float(barrier_timeout)
        if max_restarts is None:
            max_restarts = env_nonneg_int("MXNET_MAX_RESTARTS", 0)
        self._max_restarts = int(max_restarts)
        self._restarts = {}         # (role, rank) -> takeovers so far
        self._t0 = time.monotonic()
        self._cv = threading.Condition()
        self._nodes = {}            # node_id -> _Node
        self._next_id = 0
        self._barriers = {}         # name -> {"gen": int, "arrived": set}
        self._barrier_errors = {}   # (name, gen) -> message
        self._stop = threading.Event()
        self._fanned_out = False
        self._conns = set()         # live client connections
        # data-plane shard leases (ISSUE 17): dataset name -> lease book
        self._datasets = {}
        # elastic scale directives (ISSUE 18): role -> latest directive
        self._scale = {}
        self._data_ttl = env_positive_float("MXNET_DATA_LEASE_TTL", 30.0)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.addr = "%s:%d" % self._sock.getsockname()[:2]

    # -- lifecycle log -------------------------------------------------------
    def _lifecycle(self, event, **fields):
        """One structured timeline line on the scheduler's stdout (the
        launcher inherits it), e.g.
        ``[lifecycle] t=+12.3s event=dead role=worker rank=1 ...``."""
        parts = ["[lifecycle]", "t=+%.1fs" % (time.monotonic() - self._t0),
                 "event=%s" % event]
        parts += ["%s=%s" % (k, v) for k, v in fields.items()]
        print(" ".join(parts), flush=True)

    # -- state helpers (lock held) -------------------------------------------
    def _num_dead_locked(self):
        # only worker/server deaths count: num_dead_node is the TRAINING
        # job's parity signal (ref ps-lite get_num_dead_node) — a dead
        # serving replica is the router's problem, not the optimizer's
        return sum(1 for n in self._nodes.values()
                   if n.role in SLOTTED_ROLES
                   and not n.alive and not n.done and not n.replaced)

    def _servers_locked(self):
        return sorted((n for n in self._nodes.values()
                       if n.role == "server" and not n.replaced),
                      key=lambda n: n.rank)

    def _respawnable_locked(self, node):
        """Can this dead node still be replaced by a respawn? True only
        in elastic mode while the (role, rank) restart budget lasts."""
        if node.done or node.replaced or self._max_restarts <= 0:
            return False
        used = self._restarts.get((node.role, node.rank), 0)
        return used < self._max_restarts

    def _abort_barrier_locked(self, name, msg):
        b = self._barriers.get(name)
        if b is None or not b["arrived"]:
            return
        b["ts"] = time.monotonic()  # abort = activity: waiters still
        # need this round's error record (see prune_barrier_names)
        self._barrier_errors[(name, b["gen"])] = msg
        # prune: keep only the newest few abort records
        while len(self._barrier_errors) > 32:
            self._barrier_errors.pop(next(iter(self._barrier_errors)))
        b["gen"] += 1
        b["arrived"] = set()
        self._cv.notify_all()

    def _mark_dead_locked(self, node_id, why):
        node = self._nodes.get(node_id)
        if node is None or node.done or not node.alive or node.replaced:
            return
        node.alive = False
        respawnable = self._respawnable_locked(node)
        self._lifecycle("dead", role=node.role, rank=node.rank,
                        node=node_id, cause="\"%s\"" % why,
                        respawn="pending" if respawnable else "none")
        if respawnable:
            # elastic mode: the round survives — the dead node's pending
            # arrivals are retracted so its respawn must re-arrive, and
            # every waiter keeps waiting (bounded by its own timeout)
            for b in self._barriers.values():
                b["arrived"].discard(node_id)
        else:
            for name in list(self._barriers):
                self._abort_barrier_locked(
                    name, "barrier %r broken: %s %d (rank %d) died (%s)"
                    % (name, node.role, node_id, node.rank, why))
        if node.role == "worker":
            self._data_release_rank_locked(node.rank, "death")
        self._cv.notify_all()
        self._maybe_finish_locked()

    def _data_release_rank_locked(self, rank, cause):
        """Return a dead/leaving worker's shard leases to the pool with
        their committed cursors — the rebalance that lets a survivor or
        the rank's own respawn resume mid-shard."""
        now = time.monotonic()
        for book in self._datasets.values():
            released = book.release_owner(rank, now)
            if released:
                self._lifecycle(
                    "data-rebalance", dataset=book.name, rank=rank,
                    cause=cause,
                    shards=",".join(str(r["shard"]) for r in released),
                    cursors=",".join(str(r["cursor"]) for r in released))

    def _maybe_finish_locked(self):
        """All expected workers done (or dead beyond recovery) =>
        shutdown fan-out. A dead worker whose rank can still be
        respawned holds the job open — tearing the servers down while
        the launcher is mid-respawn would turn a recoverable crash into
        a job failure."""
        if self._num_workers <= 0:
            # a serving-fleet tracker (launch.py --serve): no training
            # workers exist, so "all workers done" is vacuously true on
            # the FIRST done/dead event — the fleet is torn down
            # explicitly (stop op / launcher), never by worker count
            return
        workers = [n for n in self._nodes.values()
                   if n.role == "worker" and not n.replaced]
        if len(workers) < self._num_workers or self._fanned_out:
            return
        if all(n.done or (not n.alive and not self._respawnable_locked(n))
               for n in workers):
            self._fanned_out = True
            servers = [(n.node_id, n.addr)
                       for n in self._servers_locked() if n.addr]
            threading.Thread(target=self._fan_out_stop, args=(servers,),
                             daemon=True).start()

    def _fan_out_stop(self, servers):
        """Send the kvstore_server protocol 'stop' to every server, then
        stop the tracker itself (graceful job teardown). A stop-acked
        server is marked done here — its own 'done' report would race
        the tracker shutdown and the timeline would log a spurious
        'dead' for a gracefully stopped server."""
        for node_id, addr in servers:
            try:
                s = connect_with_backoff(addr, deadline=5.0)
                try:
                    # kvstore_server wire: (op, key, meta, wire) 4-tuple
                    _send_msg(s, ("stop", None, None, None))
                    s.settimeout(5.0)
                    _recv_msg(s)
                finally:
                    s.close()
            except (TrackerError, OSError, ConnectionError):
                continue  # server already gone
            self._op_done({"node_id": node_id})
        self.shutdown()

    # -- op handlers ---------------------------------------------------------
    def _role_nodes_locked(self, role):
        return [n for n in self._nodes.values()
                if n.role == role and not n.replaced]

    def _takeover_locked(self, old, restart, addr):
        """Replace a dead node with its respawned incarnation: same
        rank, fresh node_id (and, for servers, a fresh published
        addr)."""
        old.replaced = True
        key = (old.role, old.rank)
        self._restarts[key] = self._restarts.get(key, 0) + 1
        nid = self._next_id
        self._next_id += 1
        node = _Node(nid, old.role, old.rank, addr, restart=restart)
        self._nodes[nid] = node
        self._lifecycle("respawned", role=node.role, rank=node.rank,
                        node=nid, restart=restart,
                        replaces=old.node_id,
                        restarts_used="%d/%d" % (self._restarts[key],
                                                 self._max_restarts))
        return node

    def _op_register(self, conn_nodes, p):
        role = p.get("role")
        if not isinstance(role, str) or not role or role == "scheduler":
            raise ValueError("register: bad role %r" % (role,))
        want = p.get("rank")
        restart = int(p.get("restart") or 0)
        addr = p.get("addr")
        info = p.get("info")
        if info is not None and not isinstance(info, dict):
            raise ValueError("register: info must be a dict")
        # slotted roles draw ranks from the fixed worker/server pools;
        # every other role (replica, ...) is slot-free: unbounded
        # per-role ranks, no effect on the training topology's counts
        limit = None
        if role in SLOTTED_ROLES:
            limit = (self._num_workers if role == "worker"
                     else self._num_servers)
        with self._cv:
            node = None
            if want is not None:
                want = int(want)
                if want < 0 or (limit is not None and want >= limit):
                    raise ValueError(
                        "register: rank %d out of range for %d %ss"
                        % (want, limit, role))
                existing = next((n for n in self._role_nodes_locked(role)
                                 if n.rank == want), None)
                if existing is not None and existing.alive \
                        and not existing.done and restart > 0:
                    # respawn raced ahead of dead-detection of its
                    # predecessor: wait for the conn-drop/heartbeat scan
                    deadline = time.monotonic() + self.TAKEOVER_WAIT
                    while existing.alive and time.monotonic() < deadline \
                            and not self._stop.is_set():
                        self._cv.wait(timeout=0.1)
                if existing is not None:
                    # a DONE node stays alive=True forever (it is never
                    # marked dead), but its work is over: a respawn for
                    # its rank — e.g. the process exited nonzero AFTER
                    # its atexit done() — takes over instead of burning
                    # the restart budget on 'already alive' errors. A
                    # DEAD node's takeover is gated on the same elastic
                    # budget as every other respawn decision: in
                    # non-elastic mode (or past the budget) the job is
                    # already tearing itself down around this rank, and
                    # accepting the registration would report a healthy
                    # topology over a dying job.
                    can_take = restart > 0 and (
                        existing.done
                        or (not existing.alive
                            and self._respawnable_locked(existing)))
                    if can_take:
                        node = self._takeover_locked(existing, restart,
                                                     addr)
                    elif existing.alive and not existing.done:
                        raise ValueError(
                            "register: %s rank %d is already registered "
                            "and alive (node %d)"
                            % (role, want, existing.node_id))
                    else:
                        used = self._restarts.get((role, want), 0)
                        raise ValueError(
                            "register: %s rank %d cannot be taken over "
                            "(restart=%d, respawn budget %d/%d)"
                            % (role, want, restart, used,
                               self._max_restarts))
                else:
                    node = self._new_node_locked(role, want, addr, restart)
            elif restart > 0:
                # respawn that does not know its env rank: take over
                # the lowest dead-but-respawnable rank of this role
                # (budget-checked — the tracker may already have
                # aborted barriers for an over-budget rank, and a
                # takeover past MXNET_MAX_RESTARTS would register into
                # a job that is tearing itself down)
                deadline = time.monotonic() + self.TAKEOVER_WAIT
                while not self._stop.is_set():
                    dead = sorted((n for n in self._role_nodes_locked(role)
                                   if not n.alive
                                   and self._respawnable_locked(n)),
                                  key=lambda n: n.rank)
                    if dead:
                        node = self._takeover_locked(dead[0], restart, addr)
                        break
                    if time.monotonic() >= deadline:
                        raise ValueError(
                            "register: restart=%d but no dead %s rank to "
                            "take over" % (restart, role))
                    self._cv.wait(timeout=0.1)
            if node is None:
                taken = {n.rank for n in self._role_nodes_locked(role)}
                if limit is None:
                    rank = next(r for r in range(len(taken) + 1)
                                if r not in taken)
                else:
                    rank = next((r for r in range(limit)
                                 if r not in taken), None)
                    if rank is None:
                        raise ValueError(
                            "register: all %d %s ranks already assigned"
                            % (limit, role))
                node = self._new_node_locked(role, rank, addr, restart)
            if info:
                node.info = dict(info)
            conn_nodes.add(node.node_id)
            self._cv.notify_all()
        return {"node_id": node.node_id, "rank": node.rank,
                "num_workers": self._num_workers,
                "num_servers": self._num_servers}

    def _new_node_locked(self, role, rank, addr, restart):
        nid = self._next_id
        self._next_id += 1
        node = _Node(nid, role, rank, addr, restart=restart)
        self._nodes[nid] = node
        self._lifecycle("registered", role=role, rank=rank, node=nid,
                        addr=addr or "-", restart=restart)
        return node

    def _op_get_servers(self, p):
        """Block until every expected server is registered AND alive;
        return their URIs in rank order. A dead server aborts the wait
        — unless its rank can still be respawned (elastic mode), in
        which case the caller keeps waiting and receives the
        REPLACEMENT's URI once it re-registers (this is how a worker's
        RPC-retry loop re-discovers a respawned server's new port)."""
        timeout = float(p.get("timeout", 60.0))
        deadline = time.monotonic() + timeout
        with self._cv:
            while not self._stop.is_set():
                servers = self._servers_locked()
                alive = [n for n in servers if n.alive]
                if len(alive) >= self._num_servers:
                    return [n.addr for n in alive]
                dead = [n for n in servers
                        if not n.alive and not self._respawnable_locked(n)]
                if dead:
                    raise TrackerError(
                        "get_servers: server rank %d died during "
                        "rendezvous" % dead[0].rank)
                if time.monotonic() >= deadline:
                    raise TrackerError(
                        "get_servers: %d of %d servers alive within "
                        "%.0fs" % (len(alive), self._num_servers, timeout))
                self._cv.wait(timeout=0.2)
            raise TrackerError("get_servers: tracker stopped")

    def _op_heartbeat(self, conn_nodes, p):
        nid = p.get("node_id")
        with self._cv:
            node = self._nodes.get(nid)
            if node is None:
                raise ValueError("heartbeat: unknown node %r" % (nid,))
            conn_nodes.add(nid)
            node.last_beat = time.monotonic()
            return {"num_dead": self._num_dead_locked()}

    def _op_barrier(self, p):
        """All expected workers must arrive; a dead peer aborts the
        round with an error to every waiter (instead of the reference's
        infinite spin), and an overall timeout bounds the wait."""
        nid = p.get("node_id")
        name = p.get("name", "")
        timeout = float(p.get("timeout") or self._barrier_timeout)
        deadline = time.monotonic() + timeout
        with self._cv:
            b = self._barriers.setdefault(name, {"gen": 0, "arrived": set()})
            b["ts"] = time.monotonic()
            prune_barrier_names(self._barriers, self._barrier_errors, name,
                                quiescent=lambda s: not s["arrived"])
            gen = b["gen"]
            b["arrived"].add(nid)
            if len(b["arrived"]) >= self._num_workers:
                b["gen"] += 1
                b["arrived"] = set()
                self._cv.notify_all()
                return None
            while b["gen"] == gen and not self._stop.is_set():
                if time.monotonic() >= deadline:
                    msg = ("barrier %r timed out after %.0fs (%d of %d "
                           "workers arrived)"
                           % (name, timeout, len(b["arrived"]),
                              self._num_workers))
                    self._abort_barrier_locked(name, msg)
                    raise TrackerError(msg)
                self._cv.wait(timeout=0.2)
            err = self._barrier_errors.get((name, gen))
            if err is not None:
                raise TrackerError(err)
            if self._stop.is_set() and b["gen"] == gen:
                raise TrackerError("barrier %r: tracker stopped" % (name,))
            return None

    def _op_done(self, p):
        nid = p.get("node_id")
        with self._cv:
            node = self._nodes.get(nid)
            if node is not None and not node.done:
                node.done = True
                self._lifecycle("done", role=node.role, rank=node.rank,
                                node=nid)
            self._maybe_finish_locked()
        return None

    def _op_num_dead(self):
        with self._cv:
            return self._num_dead_locked()

    def _op_event(self, p):
        """Client-reported lifecycle event (e.g. a respawned server's
        ``restored-from=<ckpt>``): folded into the scheduler's timeline
        log so one stream reconstructs the whole job."""
        event = str(p.get("event", "client-event"))
        fields = p.get("fields") or {}
        if not isinstance(fields, dict):
            raise ValueError("event: fields must be a dict")
        clean = {str(k): str(v) for k, v in sorted(fields.items())}
        self._lifecycle(event, **clean)
        return None

    def _op_publish(self, p):
        """Replace a node's published metadata (the serving fleet's
        load gauge / draining state): replicas re-publish on every
        heartbeat interval and on hot-swap, routers read it through
        ``members``."""
        nid = p.get("node_id")
        info = p.get("info")
        if not isinstance(info, dict):
            raise ValueError("publish: info must be a dict")
        with self._cv:
            node = self._nodes.get(nid)
            if node is None:
                raise ValueError("publish: unknown node %r" % (nid,))
            node.info = dict(info)
            self._cv.notify_all()
        return None

    def _op_members(self, p):
        """Live view of one role's nodes (default ``replica``) with
        their published info — the FleetRouter's discovery surface.
        Sharded serving groups (ISSUE 20) ride the published info
        verbatim (``group``/``group_size``/``group_rank``); passing
        ``group`` narrows the view to that group's members so a tool
        can watch one mesh's health without filtering client-side."""
        role = p.get("role", "replica")
        group = p.get("group")
        with self._cv:
            return [{"node_id": n.node_id, "rank": n.rank, "addr": n.addr,
                     "alive": n.alive, "done": n.done,
                     "restart": n.restart, "info": dict(n.info)}
                    for n in self._nodes.values()
                    if n.role == role and not n.replaced
                    and (group is None or n.info.get("group") == group)]

    def _op_nodes(self):
        """Topology snapshot (debugging / tests)."""
        with self._cv:
            return [{"node_id": n.node_id, "role": n.role, "rank": n.rank,
                     "addr": n.addr, "alive": n.alive, "done": n.done,
                     "replaced": n.replaced, "restart": n.restart,
                     "info": dict(n.info)}
                    for n in self._nodes.values()]

    # -- data-plane shard leases (ISSUE 17) ----------------------------------
    def _data_book_locked(self, name):
        book = self._datasets.get(name)
        if book is None:
            raise ValueError("dataset %r was never data_init'd" % (name,))
        return book

    def _op_data_init(self, p):
        from .data.lease import ShardLeaseBook  # stdlib-only, lazy

        name = str(p["name"])
        shards = [int(n) for n in p["shards"]]
        with self._cv:
            book = self._datasets.get(name)
            if book is None:
                book = ShardLeaseBook(name, shards, self._data_ttl)
                self._datasets[name] = book
                self._lifecycle("data-init", dataset=name,
                                shards=len(shards),
                                records=sum(shards))
            elif book.record_counts() != shards:
                raise ValueError(
                    "dataset %r already registered with different shard "
                    "counts (%r != %r)"
                    % (name, book.record_counts(), shards))
            return {"epoch": book.epoch, "shards": len(book.shards)}

    def _op_data_acquire(self, p):
        with self._cv:
            book = self._data_book_locked(p["name"])
            got = book.acquire(int(p["rank"]), int(p["epoch"]),
                               time.monotonic())
            if got["status"] == "lease":
                self._lifecycle(
                    "data-lease", dataset=book.name, epoch=got["epoch"],
                    shard=got["shard"], rank=int(p["rank"]),
                    cursor=got["cursor"],
                    resumed=int(bool(got["resumed"])),
                    rebalanced=int(bool(got["rebalanced"])))
            return got

    def _op_data_renew(self, p):
        with self._cv:
            book = self._data_book_locked(p["name"])
            return book.renew(int(p["rank"]), int(p["epoch"]),
                              int(p["shard"]), int(p["cursor"]),
                              time.monotonic())

    def _op_data_complete(self, p):
        with self._cv:
            book = self._data_book_locked(p["name"])
            done = book.complete(int(p["rank"]), int(p["epoch"]),
                                 int(p["shard"]), int(p["cursor"]),
                                 time.monotonic())
            if done.get("epoch_done"):
                self._lifecycle("data-epoch-done", dataset=book.name,
                                epoch=int(p["epoch"]))
            return done

    def _op_data_release(self, p):
        with self._cv:
            self._data_book_locked(p["name"])  # typed unknown-name error
            self._data_release_rank_locked(int(p["rank"]), "release")
            return None

    def _op_data_state(self, p):
        with self._cv:
            return self._data_book_locked(p["name"]).snapshot()

    # -- elastic scale directives (ISSUE 18) ---------------------------------
    # The tracker is a mailbox, not a policymaker: the autoscaler writes
    # the latest desired fleet size + retired ranks here, the launch.py
    # supervisor polls it. Plain data only (the launcher reads it with a
    # stdlib unpickler), monotonically sequenced so a poller applies each
    # directive exactly once, and fail-static by construction: when no
    # directive was ever set (or the autoscaler dies) scale_get returns
    # the last word — or None — and the fleet keeps its current shape.
    def _op_scale_set(self, p):
        role = str(p.get("role", "replica"))
        desired = int(p["desired"])
        if desired < 0:
            raise ValueError("scale_set: desired must be >= 0, got %d"
                             % desired)
        retired = sorted({int(r) for r in (p.get("retired") or ())})
        with self._cv:
            prev = self._scale.get(role)
            directive = {"role": role, "desired": desired,
                         "retired": retired,
                         "seq": (prev["seq"] + 1) if prev else 1}
            self._scale[role] = directive
            self._lifecycle("scale-directive", role=role, desired=desired,
                            retired=",".join(map(str, retired)) or "-",
                            seq=directive["seq"])
            self._cv.notify_all()
            return dict(directive)

    def _op_scale_get(self, p):
        with self._cv:
            d = self._scale.get(str(p.get("role", "replica")))
            return dict(d) if d else None

    def _dispatch(self, conn_nodes, op, p):
        if op == "register":
            return self._op_register(conn_nodes, p)
        if op == "get_servers":
            return self._op_get_servers(p)
        if op == "heartbeat":
            return self._op_heartbeat(conn_nodes, p)
        if op == "barrier":
            return self._op_barrier(p)
        if op == "done":
            return self._op_done(p)
        if op == "num_dead":
            return self._op_num_dead()
        if op == "event":
            return self._op_event(p)
        if op == "publish":
            return self._op_publish(p)
        if op == "members":
            return self._op_members(p)
        if op == "nodes":
            return self._op_nodes()
        if op == "data_init":
            return self._op_data_init(p)
        if op == "data_acquire":
            return self._op_data_acquire(p)
        if op == "data_renew":
            return self._op_data_renew(p)
        if op == "data_complete":
            return self._op_data_complete(p)
        if op == "data_release":
            return self._op_data_release(p)
        if op == "data_state":
            return self._op_data_state(p)
        if op == "scale_set":
            return self._op_scale_set(p)
        if op == "scale_get":
            return self._op_scale_get(p)
        raise ValueError("unknown op %r" % (op,))

    # -- connection loop -----------------------------------------------------
    def _handle(self, conn):
        conn_nodes = set()  # node_ids bound to this connection
        try:
            while not self._stop.is_set():
                op, p = _recv_msg(conn)
                if op == "stop":
                    _send_msg(conn, ("ok", None))
                    self.shutdown()
                    return
                try:
                    payload = self._dispatch(conn_nodes, op, p or {})
                except Exception as e:
                    try:
                        _send_msg(conn, ("err", "%s: %s"
                                         % (type(e).__name__, e)))
                    except OSError:
                        raise ConnectionError("reply failed")
                    continue
                _send_msg(conn, ("ok", payload))
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            self._conns.discard(conn)
            conn.close()
            # a dropped connection kills every node bound to it (fast
            # dead detection for SIGKILLed processes; graceful exits
            # sent "done" first, which _mark_dead respects)
            with self._cv:
                for nid in conn_nodes:
                    self._mark_dead_locked(nid, "connection dropped")

    def _monitor(self):
        """Heartbeat scan: nodes whose beats stopped are dead."""
        tick = max(self._heartbeat_timeout / 4.0, 0.2)
        while not self._stop.wait(tick):
            now = time.monotonic()
            with self._cv:
                for n in list(self._nodes.values()):
                    if (n.alive and not n.done
                            and now - n.last_beat > self._heartbeat_timeout):
                        self._mark_dead_locked(n.node_id, "heartbeat lost")
                # shard leases whose holder stopped committing: back to
                # the pool (cursor intact) so survivors pick them up
                for book in self._datasets.values():
                    for r in book.expire(now):
                        self._lifecycle(
                            "data-lease-expired", dataset=book.name,
                            shard=r["shard"], rank=r["rank"],
                            cursor=r["cursor"])

    def serve_forever(self):
        self._sock.settimeout(0.5)
        threading.Thread(target=self._monitor, daemon=True).start()
        threads = []
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            self._conns.add(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=2)

    def serve_in_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def shutdown(self):
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        # closing live conns unblocks handler threads parked in recv so
        # serve_forever's joins return immediately instead of timing out
        for conn in list(self._conns):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class TrackerClient:
    """One node's connection to the scheduler: registers on construction
    (rank assignment), beats on a dedicated second connection so long
    barrier waits never starve the heartbeat, and exposes the
    rendezvous/barrier/failure-count surface."""

    def __init__(self, uri, role, addr=None,
                 connect_deadline=30.0,
                 heartbeat_interval=None, rank=None, restart_count=0,
                 info=None):
        self._uri = uri
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._done_sent = False
        # validate BEFORE connecting: a bad env knob must not leave a
        # half-registered node behind
        if heartbeat_interval is None:
            heartbeat_interval = env_positive_float(
                "MXNET_TRACKER_HEARTBEAT_INTERVAL",
                DEFAULT_HEARTBEAT_INTERVAL)
        self._sock = connect_with_backoff(uri, deadline=connect_deadline)
        payload = {"role": role, "addr": addr}
        if rank is not None:
            payload["rank"] = int(rank)
        if restart_count:
            payload["restart"] = int(restart_count)
        if info is not None:
            payload["info"] = dict(info)
        # a respawning registration may wait TAKEOVER_WAIT server-side
        # for its dead predecessor; give the rpc room beyond that
        info = self._rpc("register", payload,
                         timeout=Tracker.TAKEOVER_WAIT + 20.0)
        self.node_id = info["node_id"]
        self.rank = info["rank"]
        self.num_workers = info["num_workers"]
        self.num_servers = info["num_servers"]
        self.role = role
        self.restart_count = int(restart_count)
        # heartbeats: dedicated connection + thread
        self._hb_sock = connect_with_backoff(uri, deadline=connect_deadline)
        self._hb_thread = threading.Thread(
            target=self._beat, args=(float(heartbeat_interval),),
            daemon=True)
        self._hb_thread.start()

    def _rpc(self, op, payload=None, timeout=60.0, sock=None, lock=None):
        sock = sock or self._sock
        try:
            with (lock or self._lock):
                sock.settimeout(timeout)
                _send_msg(sock, (op, payload or {}))
                status, reply = _recv_msg(sock)
        except (socket.timeout, OSError, ConnectionError) as e:
            # a timed-out request's late reply would otherwise be read
            # as the NEXT op's reply — invalidate the connection and
            # raise the domain error kvstore.create() knows to catch
            try:
                sock.close()
            except OSError:
                pass
            raise TrackerError(
                "tracker rpc %r to %s failed (%s: %s); connection closed"
                % (op, self._uri, type(e).__name__, e))
        if status != "ok":
            raise TrackerError("tracker: %s" % (reply,))
        return reply

    def _beat(self, interval):
        from . import chaos  # stdlib-only, cycle-free

        hb_lock = threading.Lock()
        while not self._closed.wait(interval):
            if chaos.heartbeat_fault():
                continue  # injected wedge: socket stays open, beat lost
            try:
                self._rpc("heartbeat", {"node_id": self.node_id},
                          timeout=10.0, sock=self._hb_sock, lock=hb_lock)
            except (TrackerError, OSError, ConnectionError):
                return  # tracker gone; stop beating

    # -- surface -------------------------------------------------------------
    def get_server_uris(self, timeout=60.0):
        """Block until every server registered; URIs in rank order."""
        return self._rpc("get_servers", {"timeout": timeout},
                         timeout=timeout + 10.0)

    def barrier(self, name="", timeout=None):
        """Tracker barrier across all workers. Raises TrackerError on a
        dead peer or on the overall timeout — never spins forever. In
        elastic mode a dead-but-respawnable peer keeps the round open
        (its respawn re-arrives) instead of aborting it."""
        if timeout is None:
            timeout = env_positive_float("MXNET_TRACKER_BARRIER_TIMEOUT",
                                         DEFAULT_BARRIER_TIMEOUT)
        timeout = float(timeout)
        self._rpc("barrier",
                  {"node_id": self.node_id, "name": name, "timeout": timeout},
                  timeout=timeout + 15.0)

    def num_dead_node(self):
        return int(self._rpc("num_dead"))

    def nodes(self):
        return self._rpc("nodes")

    def publish(self, info):
        """Replace this node's published metadata on the scheduler (the
        replica load gauge / draining state; see ``members``)."""
        self._rpc("publish", {"node_id": self.node_id,
                              "info": dict(info)}, timeout=10.0)

    def members(self, role="replica"):
        """One role's nodes with their published info — the router's
        discovery view."""
        return self._rpc("members", {"role": role})

    def log_event(self, event, **fields):
        """Report a lifecycle event into the scheduler's timeline log
        (e.g. ``restored-from``). Best-effort: a dead tracker must not
        fail the caller's recovery path."""
        try:
            self._rpc("event", {"event": str(event),
                                "fields": {str(k): str(v)
                                           for k, v in fields.items()}},
                      timeout=10.0)
        except (TrackerError, OSError, ConnectionError):
            pass

    # -- data-plane shard leases (ISSUE 17) ---------------------------------
    # explicit-rank signatures, identical to LocalLeaseAuthority's, so
    # ShardedRecordStream speaks one surface to either authority
    def data_init(self, name, shards):
        return self._rpc("data_init",
                         {"name": str(name),
                          "shards": [int(n) for n in shards]})

    def data_acquire(self, name, rank, epoch):
        return self._rpc("data_acquire",
                         {"name": str(name), "rank": int(rank),
                          "epoch": int(epoch)})

    def data_renew(self, name, rank, epoch, shard, cursor):
        return self._rpc("data_renew",
                         {"name": str(name), "rank": int(rank),
                          "epoch": int(epoch), "shard": int(shard),
                          "cursor": int(cursor)})

    def data_complete(self, name, rank, epoch, shard, cursor):
        return self._rpc("data_complete",
                         {"name": str(name), "rank": int(rank),
                          "epoch": int(epoch), "shard": int(shard),
                          "cursor": int(cursor)})

    def data_release(self, name, rank):
        return self._rpc("data_release",
                         {"name": str(name), "rank": int(rank)})

    def data_state(self, name):
        return self._rpc("data_state", {"name": str(name)})

    # -- elastic scale directives (ISSUE 18) ---------------------------------
    def scale_set(self, desired, retired=(), role="replica"):
        """Publish the autoscaler's directive (desired size + retired
        ranks) for the launch.py supervisor to poll via ``scale_get``."""
        return self._rpc("scale_set",
                         {"role": str(role), "desired": int(desired),
                          "retired": [int(r) for r in retired]},
                         timeout=10.0)

    def scale_get(self, role="replica"):
        """Latest scale directive for ``role``, or None if none was
        ever set (the fail-static default)."""
        return self._rpc("scale_get", {"role": str(role)}, timeout=10.0)

    def done(self):
        """Report graceful completion (idempotent; swallows a dead
        tracker — at-exit teardown must never raise)."""
        if self._done_sent:
            return
        self._done_sent = True
        try:
            self._rpc("done", {"node_id": self.node_id}, timeout=10.0)
        except (TrackerError, OSError, ConnectionError):
            pass

    def close(self):
        self._closed.set()
        for s in (self._sock, self._hb_sock):
            try:
                s.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# env contract + worker-side discovery singleton
# ---------------------------------------------------------------------------
def tracker_env_spec():
    """(scheduler_uri, num_workers, num_servers) from the DMLC env, or
    None when no scheduler topology is configured. The topology exists
    exactly when DMLC_PS_ROOT_URI/PORT name the scheduler AND
    DMLC_NUM_SERVER asks for parameter servers."""
    host = os.environ.get("DMLC_PS_ROOT_URI")
    port = os.environ.get("DMLC_PS_ROOT_PORT")
    try:
        num_servers = int(os.environ.get("DMLC_NUM_SERVER", "0") or 0)
    except ValueError:
        return None
    if not host or not port or num_servers <= 0:
        return None
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1") or 1)
    return ("%s:%s" % (host, port), num_workers, num_servers)


_WORKER_CLIENT = None
_WORKER_CLIENT_LOCK = threading.Lock()


def worker_client():
    """This process's TrackerClient (role=worker), created on first use
    from the env contract; None when no scheduler topology is
    configured. Registers an atexit hook that reports ``done`` so the
    scheduler can fan out shutdown to the servers.

    Under ``tools/launch.py`` the env names this worker's rank
    (``DMLC_WORKER_ID``) and incarnation (``DMLC_RESTART_COUNT``); a
    respawned worker therefore takes over exactly its predecessor's
    rank — the rank whose progress the checkpoint recorded."""
    global _WORKER_CLIENT
    with _WORKER_CLIENT_LOCK:
        if _WORKER_CLIENT is not None:
            return _WORKER_CLIENT
        spec = tracker_env_spec()
        if spec is None:
            return None
        uri, _nw, _ns = spec
        rank = os.environ.get("DMLC_WORKER_ID",
                              os.environ.get("DMLC_RANK"))
        restart = env_nonneg_int("DMLC_RESTART_COUNT", 0)
        client = TrackerClient(uri, "worker",
                               rank=int(rank) if rank is not None else None,
                               restart_count=restart)
        import atexit

        atexit.register(lambda: (client.done(), client.close()))
        _WORKER_CLIENT = client
        return client


def discover_server_uris(timeout=60.0):
    """Worker-side rendezvous: register with the scheduler and block
    until every parameter server has published its URI. None when no
    scheduler topology is configured in the env."""
    client = worker_client()
    if client is None:
        return None
    return client.get_server_uris(timeout=timeout)


# ---------------------------------------------------------------------------
# scheduler entry point (DMLC_ROLE=scheduler)
# ---------------------------------------------------------------------------
def main():
    host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "0"))
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1") or 1)
    num_servers = int(os.environ.get("DMLC_NUM_SERVER", "0") or 0)
    hb_timeout = env_positive_float("MXNET_TRACKER_HEARTBEAT_TIMEOUT",
                                    DEFAULT_HEARTBEAT_TIMEOUT)
    max_restarts = env_nonneg_int("MXNET_MAX_RESTARTS", 0)
    # bind-anywhere: the advertised host may be this host's external
    # name; bind the wildcard so both loopback and external connects work
    bind_host = "" if host not in ("127.0.0.1", "localhost") else host
    tracker = Tracker(host=bind_host, port=port, num_workers=num_workers,
                      num_servers=num_servers,
                      heartbeat_timeout=hb_timeout,
                      max_restarts=max_restarts)
    print("tracker listening on %s (workers=%d servers=%d "
          "max_restarts=%d)"
          % (tracker.addr, num_workers, num_servers, max_restarts),
          flush=True)
    tracker.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
