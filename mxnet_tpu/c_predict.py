"""Python half of the C predict ABI.

Reference counterpart: ``src/c_api/c_predict_api.cc`` (364 LoC) backing
``include/mxnet/c_predict_api.h``. TPU-native split: the C shared library
(``src/c_predict.cc`` → libmxtpu_predict.so) owns the ABI surface and
embeds CPython; this module owns everything behind it — symbol JSON
parsing, param loading, binding the jitted XLA inference program. A C
deployment links one .so and never sees Python, while the compiled
program underneath is the same HloModule the framework trains with.

Since ISSUE 6 the bind path is the serving tier's
:class:`~mxnet_tpu.serving.AOTPredictor` in exact-shape mode
(``ladder=None``): the C ABI and the dynamic-batching server share one
predictor — constant folding, weight layout freezing, and the
``get_internals`` partial-output selection behave identically on both
surfaces.
"""
from __future__ import annotations

import ctypes
import io

import numpy as _np


def _as_ndarray_map(param_bytes):
    """Parse a .params payload (dict save format; arg:/aux: prefixes
    per reference save_checkpoint, model.py:366)."""
    from .ndarray.ndarray import array

    arg_params, aux_params = {}, {}
    with _np.load(io.BytesIO(param_bytes), allow_pickle=False) as npz:
        for k in npz.keys():
            if k.startswith("arg:"):
                arg_params[k[4:]] = array(npz[k])
            elif k.startswith("aux:"):
                aux_params[k[4:]] = array(npz[k])
            else:
                arg_params[k] = array(npz[k])
    return arg_params, aux_params


class CPredictor:
    """One bound inference program (the PredictorHandle's payload).

    A thin ABI adapter over the serving tier's AOT predictor bound at
    the exact ``input_shapes`` (``ladder=None``): no padding, no bucket
    selection — the reference's fixed-shape PredictorHandle contract —
    but the same constant-folded, layout-frozen compiled forward the
    dynamic-batching server runs."""

    def __init__(self, symbol_json, param_bytes, dev_type, dev_id,
                 input_shapes, output_names=None):
        from . import context as ctx_mod
        from . import symbol as sym_mod
        from .serving import AOTPredictor

        sym = sym_mod.load_json(symbol_json)
        # dev_type follows the reference enum: 1=cpu, 2=gpu(=accelerator)
        ctx = ctx_mod.cpu(dev_id) if dev_type == 1 else ctx_mod.gpu(dev_id)
        arg_params, aux_params = _as_ndarray_map(param_bytes)
        self._pred = AOTPredictor(
            sym, arg_params, aux_params, data_shapes=dict(input_shapes),
            ladder=None, device=ctx,
            output_names=list(output_names) if output_names else None)
        self._input_shapes = {k: tuple(v) for k, v in
                              dict(input_shapes).items()}
        self._inputs = {k: _np.zeros(v, _np.float32)
                        for k, v in self._input_shapes.items()}
        self._outputs = None

    # -- ABI backend methods (called from src/c_predict.cc) -----------------
    def set_input(self, key, ptr, size):
        if key not in self._input_shapes:
            raise ValueError("unknown input %r" % key)
        shape = self._input_shapes[key]
        n = 1
        for s in shape:
            n *= s
        if size != n:
            raise ValueError("input %r: expected %d floats, got %d"
                             % (key, n, size))
        buf = (ctypes.c_float * size).from_address(ptr)
        self._inputs[key] = _np.frombuffer(
            buf, dtype=_np.float32).reshape(shape).copy()
        self._outputs = None  # stale against the new input

    def forward(self):
        self._outputs = [_np.asarray(o, dtype=_np.float32)
                         for o in self._pred.predict(self._inputs)]

    def num_outputs(self):
        return self._pred.num_outputs

    def output_shape(self, index):
        if self._outputs is None:
            self.forward()
        return tuple(int(s) for s in self._outputs[index].shape)

    def get_output(self, index, ptr, size):
        if self._outputs is None:
            raise ValueError("call forward before get_output")
        flat = _np.ascontiguousarray(self._outputs[index]).reshape(-1)
        if size != flat.size:
            raise ValueError("output %d: expected %d floats, got %d"
                             % (index, flat.size, size))
        buf = (ctypes.c_float * size).from_address(ptr)
        _np.frombuffer(buf, dtype=_np.float32)[:] = flat


class NDList:
    """Backing for MXNDListCreate/Get (a loaded .params blob)."""

    def __init__(self, nd_bytes):
        self.keys = []
        self.arrays = []
        with _np.load(io.BytesIO(nd_bytes), allow_pickle=False) as npz:
            for k in npz.keys():
                name = k.split(":", 1)[1] if ":" in k else k
                self.keys.append(name)
                self.arrays.append(
                    _np.ascontiguousarray(npz[k]).astype(_np.float32))

    def __len__(self):
        return len(self.keys)

    def key(self, i):
        return self.keys[i]

    def shape(self, i):
        return tuple(int(s) for s in self.arrays[i].shape)

    def data_ptr(self, i):
        # the ndarray owns the buffer; valid while this NDList lives
        return self.arrays[i].ctypes.data


def create_predictor(symbol_json, param_bytes, dev_type, dev_id,
                     input_shapes, output_names=None):
    return CPredictor(symbol_json, param_bytes, dev_type, dev_id,
                      input_shapes, output_names)


def create_ndlist(nd_bytes):
    return NDList(nd_bytes)
