"""Attribute scoping for symbols (``mx.AttrScope``).

Reference counterpart: ``python/mxnet/attribute.py`` — a context manager
stamping user attributes (``ctx_group``, ``lr_mult``, …) onto every
symbol created inside the scope. ``ctx_group`` is how the reference
expresses manual model parallelism (``group2ctx``, SURVEY §2.4); the
executor maps ctx groups onto mesh submeshes.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current_attrs"]

_TLS = threading.local()


def _stack():
    if not hasattr(_TLS, "stack"):
        _TLS.stack = []
    return _TLS.stack


def current_attrs():
    """Merged attrs of all active scopes (inner wins)."""
    out = {}
    for scope in _stack():
        out.update(scope._attrs)
    return out


class AttrScope:
    """``with mx.AttrScope(ctx_group='dev1', lr_mult='0.1'): ...``"""

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            if not isinstance(v, str):
                kwargs[k] = str(v)
        self._attrs = kwargs

    def get(self, attr):
        """Merge scope attrs into an explicit attr dict (scope loses)."""
        out = current_attrs()
        out.update(self._attrs)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, ptype, value, trace):
        _stack().pop()
        return False
