"""Optimizers + Updater.

Reference counterpart: ``python/mxnet/optimizer.py`` (1,210 LoC): Optimizer
registry, per-parameter lr/wd multipliers, multi-precision fp32 master
weights, Updater with state checkpointing. Each optimizer's math runs
through the registered update *ops* (ops/optimizer_ops.py) so the update is
one fused XLA kernel per parameter — the TPU analogue of the reference's
``sgd_mom_update`` CUDA kernels.
"""
from __future__ import annotations

import pickle

import numpy

from .base import MXNetError
from .ndarray import ndarray as nd
from .ndarray.ndarray import NDArray, invoke

_OPT_REGISTRY = {}


def register(klass):
    _OPT_REGISTRY[klass.__name__.lower()] = klass
    return klass


class Optimizer:
    """Base optimizer (ref: optimizer.py Optimizer)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False, param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self.multi_precision = multi_precision
        self._index_update_count = {}
        self.idx2name = dict(param_idx2name or {})
        self.sym_info = ()
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    @staticmethod
    def register(klass):
        return register(klass)

    @staticmethod
    def create_optimizer(name, **kwargs):
        return create(name, **kwargs)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype == numpy.float16:
            w32 = weight.astype(numpy.float32)
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == numpy.float16:
            inner_state, w32 = state
            g32 = grad.astype(numpy.float32)
            self.update(index, w32, g32, inner_state)
            w32.copyto(weight)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; use lr_scheduler to change lr")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler is not None else self.lr
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= self.param_dict[name].lr_mult
        else:
            lr *= self.lr_mult.get(name, 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= self.param_dict[name].wd_mult
        else:
            wd *= self.wd_mult.get(name, 1.0)
        return wd

    def _common_kwargs(self, index):
        kw = {
            "lr": self._get_lr(index),
            "wd": self._get_wd(index),
            "rescale_grad": self.rescale_grad,
        }
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


@register
class SGD(Optimizer):
    """SGD with momentum, optional multi-precision (ref: optimizer.py SGD)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        from .ndarray.sparse import RowSparseNDArray, sgd_update_rsp

        self._update_count(index)
        kw = self._common_kwargs(index)
        if isinstance(grad, RowSparseNDArray) and self.lazy_update:
            # lazy row-sparse update: only rows present in grad change
            # (ref: optimizer_op.cc sparse sgd_update FComputeEx)
            sgd_update_rsp(weight, grad, kw["lr"], wd=kw["wd"],
                           rescale_grad=kw["rescale_grad"],
                           clip_gradient=kw.get("clip_gradient"),
                           state=state, momentum=self.momentum)
        elif state is not None:
            invoke("sgd_mom_update", [weight, grad, state], dict(kw, momentum=self.momentum), out=weight)
        else:
            invoke("sgd_update", [weight, grad], kw, out=weight)

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == numpy.float16:
            self._update_count(index)
            kw = self._common_kwargs(index)
            inner, w32 = state
            if inner is not None:
                invoke("mp_sgd_mom_update", [weight, grad, inner, w32], dict(kw, momentum=self.momentum), out=weight)
            else:
                invoke("mp_sgd_update", [weight, grad, w32], kw, out=weight)
        else:
            self.update(index, weight, grad, state)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is not None:
            invoke("signum_update", [weight, grad, state], dict(kw, momentum=self.momentum, wd_lh=self.wd_lh), out=weight)
        else:
            invoke("signsgd_update", [weight, grad], kw, out=weight)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (ref: optimizer.py NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        g = g + wd * weight
        if state is not None:
            state *= self.momentum
            state += g
            g = g + self.momentum * state
        weight -= lr * g


@register
class SGLD(Optimizer):
    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        noise = nd.invoke("_random_normal", [], {"loc": 0.0, "scale": float(numpy.sqrt(lr)), "shape": weight.shape}, ctx=weight.ctx)
        weight -= lr / 2 * (g + wd * weight)
        weight += noise


@register
class DCASGD(Optimizer):
    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        mom, prev = state
        if mom is not None:
            mom *= self.momentum
            mom += -lr * (g + wd * weight + self.lamda * g * g * (weight - prev))
        else:
            mom = -lr * (g + wd * weight + self.lamda * g * g * (weight - prev))
        prev[:] = weight
        weight += mom


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
        )

    def update(self, index, weight, grad, state):
        from .ndarray.sparse import RowSparseNDArray, adam_update_rsp

        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        mean, var = state
        if isinstance(grad, RowSparseNDArray) and self.lazy_update:
            # lazy row-sparse Adam (ref: optimizer_op.cc adam FComputeEx)
            adam_update_rsp(weight, grad, mean, var, kw["lr"],
                            beta1=self.beta1, beta2=self.beta2,
                            epsilon=self.epsilon, wd=kw["wd"],
                            rescale_grad=kw["rescale_grad"],
                            clip_gradient=kw.get("clip_gradient"), t=t)
            return
        coef1 = 1.0 - self.beta1**t
        coef2 = 1.0 - self.beta2**t
        kw["lr"] *= numpy.sqrt(coef2) / coef1
        invoke(
            "adam_update",
            [weight, grad, mean, var],
            dict(kw, beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon),
            out=weight,
        )


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        g = g + wd * weight
        state += g * g
        weight -= lr * g / (state.sqrt() + self.float_stable_eps)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (
                nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
            )
        return nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        kw["gamma1"] = self.gamma1
        kw["epsilon"] = self.epsilon
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g_st, delta = state
            invoke("rmspropalex_update", [weight, grad, n, g_st, delta], dict(kw, gamma2=self.gamma2), out=weight)
        else:
            invoke("rmsprop_update", [weight, grad, state], kw, out=weight)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g *= self.rho
        acc_g += (1 - self.rho) * g * g
        delta = ((acc_delta + self.epsilon).sqrt() / (acc_g + self.epsilon).sqrt()) * g
        acc_delta *= self.rho
        acc_delta += (1 - self.rho) * delta * delta
        weight -= delta + wd * weight


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),  # z
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),  # n
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        z, n = state
        invoke("ftrl_update", [weight, grad, z, n], dict(kw, lamda1=self.lamda1, beta=self.beta), out=weight)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1**t)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        m_t *= self.beta1
        m_t += (1.0 - self.beta1) * g
        u_t[:] = nd.invoke("broadcast_maximum", [self.beta2 * u_t, g.abs()], {})
        weight -= lr * m_t / u_t


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
            nd.zeros(weight.shape, ctx=weight.ctx, dtype=weight.dtype),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t *= self.beta1
        m_t += (1.0 - self.beta1) * g
        v_t *= self.beta2
        v_t += (1.0 - self.beta2) * g * g
        g_prime = g / (1.0 - self.m_schedule)
        m_t_prime = m_t / (1.0 - m_schedule_next)
        v_t_prime = v_t / (1.0 - self.beta2**t)
        m_t_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_t_prime
        weight -= lr * m_t_bar / (v_t_prime.sqrt() + self.epsilon)


@register
class Test(Optimizer):
    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


# aliases (ref registry names)
_OPT_REGISTRY["ccsgd"] = SGD
ccSGD = SGD


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    klass = _OPT_REGISTRY.get(name.lower())
    if klass is None:
        raise MXNetError("unknown optimizer %r" % name)
    return klass(**kwargs)


class Updater:
    """Applies an optimizer, owning per-index state (ref: optimizer.py
    get_updater / Updater with set_states/get_states)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    def set_states(self, states):
        from .checkpoint import unwrap_states_map

        self.set_states_from_map(unwrap_states_map(pickle.loads(states)))

    def set_states_from_map(self, states_map):
        """Install states from a plain {index: numpy/scalar pytree} map.

        The pickle-free entry point: kvstore_server's ``load_opt``
        decodes its wire format (dtype/shape/bytes triples — never a
        pickle) into such a map, so optimizer state arriving over the
        network is installed without ever calling ``pickle.loads`` on
        peer-controlled bytes."""
        def _to_nd(x):
            if isinstance(x, numpy.ndarray):
                return nd.array(x)
            if isinstance(x, (list, tuple)):
                return type(x)(_to_nd(i) for i in x)
            return x

        self.states = {k: _to_nd(v) for k, v in states_map.items()}
        self.states_synced = {k: True for k in self.states}

    def get_states_map(self):
        """Plain {index: numpy/scalar pytree} snapshot of the states
        (the pickle-free counterpart of set_states_from_map)."""
        def _to_np(x):
            if isinstance(x, NDArray):
                return x.asnumpy()
            if isinstance(x, (list, tuple)):
                return type(x)(_to_np(i) for i in x)
            return x

        return {k: _to_np(v) for k, v in self.states.items()}

    def get_states(self, dump_optimizer=False):
        return pickle.dumps(self.get_states_map())


def get_updater(optimizer):
    return Updater(optimizer)
