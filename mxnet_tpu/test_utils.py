"""Test utilities.

Reference counterpart: ``python/mxnet/test_utils.py`` (1,540 LoC):
check_numeric_gradient (finite differences vs backward, :789),
check_symbolic_forward/backward (:921/:995), check_consistency (:1203 —
cross-context equivalence), rand_ndarray, assert_almost_equal.
"""
from __future__ import annotations

import numpy as np

from . import context as ctx_mod
from .base import MXNetError
from .ndarray import ndarray as nd
from .symbol.symbol import Symbol


def default_context():
    return ctx_mod.current_context()


def default_dtype():
    return np.float32


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (
        np.random.randint(1, dim0 + 1),
        np.random.randint(1, dim1 + 1),
        np.random.randint(1, dim2 + 1),
    )


def rand_ndarray(shape, stype="default", density=None, dtype=None, ctx=None):
    if stype == "default":
        return nd.array(np.random.uniform(-1, 1, shape), ctx=ctx, dtype=dtype or np.float32)
    from .ndarray import sparse as sp

    density = 0.5 if density is None else density
    arr = np.random.uniform(-1, 1, shape).astype(dtype or np.float32)
    mask = np.random.uniform(0, 1, (shape[0],) + (1,) * (len(shape) - 1)) < density
    arr = arr * mask
    if stype == "row_sparse":
        return sp.cast_storage(nd.array(arr, ctx=ctx), "row_sparse")
    if stype == "csr":
        mask2 = np.random.uniform(0, 1, shape) < density
        return sp.cast_storage(nd.array(arr * mask2, ctx=ctx), "csr")
    raise MXNetError("unknown stype %r" % stype)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    a = a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)
    b = b.asnumpy() if hasattr(b, "asnumpy") else np.asarray(b)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol, err_msg="%s vs %s" % names)


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    a = a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)
    b = b.asnumpy() if hasattr(b, "asnumpy") else np.asarray(b)
    return np.allclose(a, b, rtol=rtol, atol=atol)


def same(a, b):
    a = a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)
    b = b.asnumpy() if hasattr(b, "asnumpy") else np.asarray(b)
    return np.array_equal(a, b)


def _parse_location(sym, location, ctx, dtype=np.float32):
    if isinstance(location, dict):
        wrong = set(location.keys()) - set(sym.list_arguments())
        if wrong:
            raise ValueError("unknown argument names %s" % wrong)
        return {
            k: (v if isinstance(v, nd.NDArray) else nd.array(v, ctx=ctx, dtype=dtype))
            for k, v in location.items()
        }
    return {
        k: (v if isinstance(v, nd.NDArray) else nd.array(v, ctx=ctx, dtype=dtype))
        for k, v in zip(sym.list_arguments(), location)
    }


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-5,
                           aux_states=None, ctx=None, dtype=np.float32):
    """Run bound forward and compare with expected numpy arrays
    (ref: test_utils.py:921)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx, dtype)
    aux = None
    if aux_states is not None:
        aux = {
            k: (v if isinstance(v, nd.NDArray) else nd.array(v, ctx=ctx, dtype=dtype))
            for k, v in aux_states.items()
        }
    else:
        aux_names = sym.list_auxiliary_states()
        if aux_names:
            shapes = {k: v.shape for k, v in location.items()}
            _, _, aux_shapes = sym.infer_shape(**shapes)
            aux = {n: nd.zeros(s, ctx=ctx) for n, s in zip(aux_names, aux_shapes)}
    executor = sym.bind(ctx=ctx, args=location, aux_states=aux)
    outputs = executor.forward(is_train=False)
    for output, expect in zip(outputs, expected):
        assert_almost_equal(output, expect, rtol=rtol, atol=atol)
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-5, grad_req="write", ctx=None, aux_states=None,
                            dtype=np.float32):
    """Run backward and compare input grads (ref: test_utils.py:995)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx, dtype)
    args_grad = {k: nd.zeros(v.shape, ctx=ctx, dtype=dtype) for k, v in location.items()}
    aux = None
    aux_names = sym.list_auxiliary_states()
    if aux_names:
        if aux_states is not None:
            aux = {k: nd.array(v, ctx=ctx, dtype=dtype) for k, v in aux_states.items()}
        else:
            shapes = {k: v.shape for k, v in location.items()}
            _, _, aux_shapes = sym.infer_shape(**shapes)
            aux = {n: nd.zeros(s, ctx=ctx) for n, s in zip(aux_names, aux_shapes)}
    executor = sym.bind(ctx=ctx, args=location, args_grad=args_grad,
                        grad_req=grad_req, aux_states=aux)
    executor.forward(is_train=True)
    og = out_grads
    if og is not None:
        og = [
            g if isinstance(g, nd.NDArray) else nd.array(g, ctx=ctx, dtype=dtype)
            for g in (og if isinstance(og, (list, tuple)) else [og])
        ]
    executor.backward(og)
    if isinstance(expected, dict):
        for name, expect in expected.items():
            if executor.grad_dict.get(name) is not None:
                assert_almost_equal(executor.grad_dict[name], expect, rtol=rtol, atol=atol)
    else:
        for name, expect in zip(sym.list_arguments(), expected):
            if expect is not None and executor.grad_dict.get(name) is not None:
                assert_almost_equal(executor.grad_dict[name], expect, rtol=rtol, atol=atol)
    return executor.grad_arrays


def numeric_grad(executor, location, aux_states=None, eps=1e-4, use_forward_train=True):
    """Central finite differences on the bound executor (ref: test_utils.py numeric_grad)."""
    approx_grads = {k: np.zeros(v.shape, dtype=np.float32) for k, v in location.items()}
    for k, v in location.items():
        old_value = np.array(v.asnumpy())  # writable copy
        flat = old_value.reshape(-1)
        grad_flat = approx_grads[k].reshape(-1)
        for i in range(flat.size):
            orig = flat[i].copy()
            flat[i] = orig + eps / 2
            executor.arg_dict[k][:] = nd.array(old_value.reshape(v.shape))
            f_pos = sum(o.asnumpy().sum() for o in executor.forward(is_train=use_forward_train))
            flat[i] = orig - eps / 2
            executor.arg_dict[k][:] = nd.array(old_value.reshape(v.shape))
            f_neg = sum(o.asnumpy().sum() for o in executor.forward(is_train=use_forward_train))
            grad_flat[i] = (f_pos - f_neg) / eps
            flat[i] = orig
        executor.arg_dict[k][:] = nd.array(old_value.reshape(v.shape))
    return approx_grads


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None,
                           use_forward_train=True, ctx=None, dtype=np.float32):
    """Finite-difference gradient check (ref: test_utils.py:789)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx, dtype)
    if grad_nodes is None:
        grad_nodes = [k for k in location]
    args_grad = {k: nd.zeros(v.shape, ctx=ctx, dtype=dtype) for k, v in location.items()}
    aux = None
    aux_names = sym.list_auxiliary_states()
    if aux_names:
        shapes = {k: v.shape for k, v in location.items()}
        _, _, aux_shapes = sym.infer_shape(**shapes)
        aux = {n: nd.zeros(s, ctx=ctx) for n, s in zip(aux_names, aux_shapes)}
        if aux_states:
            for k, v in aux_states.items():
                aux[k] = nd.array(v, ctx=ctx)
    executor = sym.bind(ctx=ctx, args=location, args_grad=args_grad, aux_states=aux)
    executor.forward(is_train=use_forward_train)
    executor.backward()
    symbolic_grads = {k: executor.grad_dict[k].asnumpy() for k in grad_nodes}

    fd_grads = numeric_grad(
        executor, {k: v for k, v in location.items() if k in grad_nodes},
        eps=numeric_eps, use_forward_train=use_forward_train,
    )
    for name in grad_nodes:
        np.testing.assert_allclose(
            fd_grads[name], symbolic_grads[name], rtol=rtol, atol=atol if atol is not None else 1e-4,
            err_msg="numeric vs symbolic gradient mismatch for %s" % name,
        )


def check_consistency(sym, ctx_list, scale=1.0, grad_req="write", rtol=1e-4, atol=1e-4):
    """Run the same graph on several contexts and compare outputs
    (ref: test_utils.py:1203 — cpu↔gpu becomes cpu↔tpu here)."""
    if len(ctx_list) < 2:
        return
    results = []
    arg_np = None
    for ctx_spec in ctx_list:
        ctx = ctx_spec["ctx"]
        shapes = {k: v for k, v in ctx_spec.items() if k != "ctx" and not k.endswith("dtype")}
        arg_names = sym.list_arguments()
        arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
        if arg_np is None:
            arg_np = [np.random.normal(0, scale, size=s).astype(np.float32) for s in arg_shapes]
        args = {n: nd.array(a, ctx=ctx) for n, a in zip(arg_names, arg_np)}
        grads = {n: nd.zeros(s, ctx=ctx) for n, s in zip(arg_names, arg_shapes)}
        aux = {n: nd.zeros(s, ctx=ctx) for n, s in zip(sym.list_auxiliary_states(), aux_shapes)}
        exe = sym.bind(ctx=ctx, args=args, args_grad=grads, grad_req=grad_req, aux_states=aux)
        outs = exe.forward(is_train=True)
        exe.backward()
        results.append((
            [o.asnumpy() for o in outs],
            {n: g.asnumpy() for n, g in exe.grad_dict.items() if g is not None},
        ))
    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for a, b in zip(ref_outs, outs):
            np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)
        for n in ref_grads:
            np.testing.assert_allclose(ref_grads[n], grads[n], rtol=rtol, atol=atol)


def check_speed(sym=None, location=None, ctx=None, N=20, grad_req="write", typ="whole", **kwargs):
    """Time forward(+backward) executions (ref: test_utils.py:1129)."""
    import time

    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    args_grad = {k: nd.zeros(v.shape, ctx=ctx) for k, v in location.items()}
    exe = sym.bind(ctx=ctx, args=location, args_grad=args_grad, grad_req=grad_req)
    # warmup
    exe.forward(is_train=True)
    if typ == "whole":
        exe.backward()
    nd.waitall()
    tic = time.time()
    for _ in range(N):
        if typ == "whole":
            exe.forward_backward()
        else:
            exe.forward(is_train=False)
    for o in exe.outputs:
        o.wait_to_read()
    nd.waitall()
    return (time.time() - tic) / N


def list_gpus():
    from .context import num_tpus

    return list(range(num_tpus()))


def download(url, fname=None, dirname=None, overwrite=False):
    raise MXNetError("download: no network egress in this environment")


def clean_dist_env(repo_root=None):
    """A copy of os.environ with every distributed-topology /
    elastic-recovery knob stripped and JAX pinned to CPU — the launch
    environment for subprocess dist tests and tools/chaos_check.py
    (ONE definition: a knob family added to a private copy would leave
    the other callers inheriting the operator's stale env)."""
    import os

    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("DMLC_", "MXNET_TPU_", "MXNET_PS_", "MXNET_MAX_",
                         "MXNET_CHECKPOINT_", "MXNET_FAULT_",
                         "MXNET_EMBED_", "MXNET_DATA_",
                         "MXNET_FLEET_AUTOSCALE_", "MXNET_QOS_")):
            del env[k]
    env["JAX_PLATFORMS"] = "cpu"
    if repo_root:
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                             "")
    return env
