"""Library location / feature info (``mx.libinfo``).

Reference counterpart: ``python/mxnet/libinfo.py`` — ``find_lib_path``
locating libmxnet.so. Here the native library is the host runtime
``libmxtpu_runtime.so`` (src/, built on demand); the compute "library"
is XLA, reported via features().
"""
from __future__ import annotations

import os

__version__ = "0.1.0"


def find_lib_path():
    """Path list of the native runtime library (ref libinfo.py:find_lib_path).

    Empty list when the native runtime is unavailable (pure-Python mode) —
    the reference raises instead, but here native is optional by design.
    """
    from . import _native

    lib = _native.get_lib()
    if lib is None:
        return []
    return [_native._lib_path()]


def find_include_path():
    """Path of the C ABI header (ref libinfo.py:find_include_path)."""
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src")
    return src if os.path.isdir(src) else ""


def features():
    """Build/runtime feature flags (ref: mx.runtime.Features)."""
    import jax

    from . import _native

    return {
        "NATIVE_RUNTIME": _native.get_lib() is not None,
        "BACKEND": jax.default_backend(),
        "DEVICES": len(jax.devices()),
        "PALLAS": True,
        "DIST": True,
    }
