"""Device contexts.

Parity surface: ``include/mxnet/base.h:85-230`` (``struct Context`` with
``kCPU/kGPU/kCPUPinned/kCPUShared`` device types) and
``python/mxnet/context.py``. TPU-native design: a ``Context`` names a JAX
device (or, for sharded execution, a position in a mesh). ``mx.tpu(0)`` is
first-class; ``cpu(i)`` maps onto host-platform devices so that unit tests
can use N virtual CPU devices as distinct "chips"
(``--xla_force_host_platform_device_count``), mirroring the reference's
multi-CPU-context test pattern (SURVEY §4).
"""
from __future__ import annotations

import threading

from .base import MXNetError

_DEVTYPE_IDS = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}
_DEVID_TYPES = {v: k for k, v in _DEVTYPE_IDS.items()}


class Context:
    """A device context. Immutable, hashable, usable as a `with` scope."""

    _default_ctx = threading.local()
    devtype2str = _DEVID_TYPES
    devstr2type = _DEVTYPE_IDS

    __slots__ = ("device_type", "device_id", "_old_ctx")

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type = device_type.device_type
            self.device_id = device_type.device_id
        else:
            if isinstance(device_type, int):
                device_type = _DEVID_TYPES[device_type]
            if device_type not in _DEVTYPE_IDS:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_type = device_type
            self.device_id = int(device_id)
        self._old_ctx = None

    @property
    def device_typeid(self):
        return _DEVTYPE_IDS[self.device_type]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx
        return False

    # -- JAX device resolution ------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device.

        cpu→'cpu' backend devices (virtual multi-device under
        xla_force_host_platform_device_count); tpu→'tpu' backend if present,
        else falls back to the default backend (so code written for mx.tpu()
        runs in CPU-only CI).
        """
        import jax

        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            # under the axon TPU platform no 'cpu' backend exists — fall back
            # to the default device (host staging is handled by jax)
            devs = _backend_devices("cpu") or _backend_devices("__default__")
        elif self.device_type == "tpu":
            devs = _backend_devices("tpu") or _backend_devices("__default__")
        elif self.device_type == "gpu":
            # parity alias: gpu(i) means "accelerator i" — resolve to whatever
            # non-cpu backend exists (tpu under axon), else cpu.
            devs = (_backend_devices("gpu") or _backend_devices("tpu")
                    or _backend_devices("__default__"))
        else:
            devs = _backend_devices("__default__")
        if not devs:
            raise MXNetError("no devices for context %r" % (self,))
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Parity: mx.context.Context.empty_cache — XLA manages HBM; no-op."""

    @classmethod
    def default_ctx(cls):
        ctx = getattr(cls._default_ctx, "value", None)
        return ctx if ctx is not None else cpu()


_DEVICE_CACHE = {}
_DEVICE_CACHE_LOCK = threading.Lock()


def _backend_devices(platform):
    with _DEVICE_CACHE_LOCK:
        if platform not in _DEVICE_CACHE:
            import jax

            if "__default__" not in _DEVICE_CACHE:
                # initialize the default backend set first — querying a
                # specific platform before general init breaks plugin
                # discovery (observed with the axon TPU plugin). Only
                # this process's addressable devices are usable as
                # NDArray homes (multi-host: jax.devices() includes
                # other workers' devices).
                jax.devices()
                _DEVICE_CACHE["__default__"] = tuple(jax.local_devices())
            if platform != "__default__":
                try:
                    _DEVICE_CACHE[platform] = tuple(
                        jax.local_devices(backend=platform))
                except RuntimeError:
                    _DEVICE_CACHE[platform] = ()
        return _DEVICE_CACHE[platform]


def cpu(device_id=0):
    """Return a CPU context (ref: python/mxnet/context.py cpu())."""
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Accelerator context; on this stack an alias resolving to TPU."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """Return a TPU context — the native device type of this framework."""
    return Context("tpu", device_id)


def num_gpus():
    return len(_backend_devices("gpu"))


def num_tpus():
    return len(_backend_devices("tpu"))


def current_context():
    return Context.default_ctx()
