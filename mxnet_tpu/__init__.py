"""mxnet_tpu — a TPU-native deep learning framework with the MXNet surface.

Brand-new implementation on JAX/XLA (see SURVEY.md at repo root): NDArray
imperative layer + autograd, Symbol graph API + one-XLA-module executor,
Module and Gluon front ends, KVStore data-parallel training over device
meshes, and the reference's operator/IO/optimizer/metric surfaces.

Import convention mirrors the reference: ``import mxnet_tpu as mx``.
"""

__version__ = "0.1.0"

from . import base  # noqa: F401
from .base import MXNetError  # noqa: F401
from .context import Context, cpu, cpu_pinned, current_context, gpu, num_gpus, num_tpus, tpu  # noqa: F401

from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from . import symbol  # noqa: F401
from . import symbol as sym  # noqa: F401
from . import autograd  # noqa: F401
from . import random  # noqa: F401
from . import random as rnd  # noqa: F401
from .executor import Executor  # noqa: F401

from . import initializer  # noqa: F401
from .initializer import init  # noqa: F401
from . import optimizer  # noqa: F401
from . import optimizer as opt  # noqa: F401
from . import lr_scheduler  # noqa: F401
from . import metric  # noqa: F401
from . import callback  # noqa: F401
from . import monitor  # noqa: F401
from . import monitor as mon  # noqa: F401
from . import io  # noqa: F401
from . import recordio  # noqa: F401
from . import kvstore  # noqa: F401
from . import kvstore as kv  # noqa: F401
from . import model  # noqa: F401
from . import module  # noqa: F401
from . import module as mod  # noqa: F401
from . import gluon  # noqa: F401
from . import operator  # noqa: F401
from . import config  # noqa: F401
from . import embedding  # noqa: F401
from . import ir  # noqa: F401
from . import contrib  # noqa: F401
from . import name  # noqa: F401
from . import attribute  # noqa: F401
from .attribute import AttrScope  # noqa: F401
from . import visualization  # noqa: F401
from . import visualization as viz  # noqa: F401
from . import rtc  # noqa: F401
from . import log  # noqa: F401
from . import libinfo  # noqa: F401
from . import executor_manager  # noqa: F401
from . import storage  # noqa: F401
from . import profiler  # noqa: F401
from . import engine  # noqa: F401
from . import dist  # noqa: F401
from . import tracker  # noqa: F401
from . import chaos  # noqa: F401
from . import serving  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import CheckpointManager  # noqa: F401
from . import test_utils  # noqa: F401

from .model import load_checkpoint, save_checkpoint  # noqa: F401
from .util import is_np_array  # noqa: F401
