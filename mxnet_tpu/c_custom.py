"""C custom-op tier: ctypes marshalling behind MXCustomOpRegister /
MXCustomFunctionRecord.

Reference counterpart: ``src/operator/custom/custom.cc:50-414`` and
``custom_function.cc`` — the ABI through which ANY frontend (not just
Python) defines operators: the frontend hands the engine a table of C
callbacks (MXCallbackList) and the engine calls back with NDArray
handles. Here the engine side is this module: a registered C creator is
wrapped into a :class:`mxnet_tpu.operator.CustomOpProp` subclass whose
methods invoke the C callbacks through ctypes, so C-defined ops flow
through the exact same Custom-op path (graph + imperative + autograd)
as Python-defined ones.

Tensor traffic crosses the C boundary as NDArray handles manufactured
through the library's own public ABI (MXNDArrayCreate →
SyncCopyFromCPU → callback → SyncCopyToCPU), mirroring the reference's
handle-passing contract; callbacks mutate outputs through
MXNDArraySyncCopyFromCPU, the documented write path.

Callback layout parity (c_api.h:130-182):
- forward  ptrs/tags: in_data(0) out_data(1) aux(4); reqs per output
- backward ptrs/tags: out_grad(3) in_data(0) out_data(1) in_grad(2)
  aux(4); reqs per input
- InferShape: called with total = n_args + n_outs + n_aux entries,
  input slots prefilled, callback fills the rest (custom.cc:105-146).
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

from .base import MXNetError

# -- ABI types (c_api.h) ----------------------------------------------------
_GenericFunc = ctypes.CFUNCTYPE(ctypes.c_int)


class MXCallbackList(ctypes.Structure):
    _fields_ = [
        ("num_callbacks", ctypes.c_int),
        ("callbacks", ctypes.POINTER(_GenericFunc)),
        ("contexts", ctypes.POINTER(ctypes.c_void_p)),
    ]


# enum CustomOpCallbacks / CustomOpPropCallbacks / CustomFunctionCallbacks
K_OP_DELETE, K_OP_FORWARD, K_OP_BACKWARD = range(3)
(K_PROP_DELETE, K_PROP_LIST_ARGS, K_PROP_LIST_OUTS, K_PROP_LIST_AUX,
 K_PROP_INFER_SHAPE, K_PROP_BWD_DEP, K_PROP_CREATE_OP,
 K_PROP_INFER_TYPE) = range(8)
K_FUNC_BACKWARD, K_FUNC_DELETE = range(2)

_c_int_p = ctypes.POINTER(ctypes.c_int)
_c_uint_p = ctypes.POINTER(ctypes.c_uint)

PropCreator = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
    ctypes.POINTER(MXCallbackList))
ListFunc = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.POINTER(ctypes.POINTER(ctypes.c_char_p)),
    ctypes.c_void_p)
InferShapeFunc = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, _c_int_p, ctypes.POINTER(_c_uint_p),
    ctypes.c_void_p)
InferTypeFunc = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, _c_int_p, ctypes.c_void_p)
BwdDepFunc = ctypes.CFUNCTYPE(
    ctypes.c_int, _c_int_p, _c_int_p, _c_int_p, _c_int_p,
    ctypes.POINTER(_c_int_p), ctypes.c_void_p)
CreateFunc = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(_c_uint_p),
    _c_int_p, _c_int_p, ctypes.POINTER(MXCallbackList), ctypes.c_void_p)
FBFunc = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_void_p), _c_int_p,
    _c_int_p, ctypes.c_int, ctypes.c_void_p)
FuncBwdFunc = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ctypes.POINTER(ctypes.c_void_p), _c_int_p, ctypes.c_int, ctypes.c_void_p)
DelFunc = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)

_REQ_TO_INT = {"null": 0, "write": 1, "inplace": 2, "add": 3,
               0: 0, 1: 1, 2: 2, 3: 3}
_DTYPE_TO_ID = {"float32": 0, "float64": 1, "float16": 2, "uint8": 3,
                "int32": 4, "int8": 5, "int64": 6, "bfloat16": 2}
_DTYPE_FROM_ID = {0: np.float32, 1: np.float64, 2: np.float16, 3: np.uint8,
                  4: np.int32, 5: np.int8, 6: np.int64}

_LIB = None


def _lib():
    """The c_api shared library — loaded by path; when this module runs
    embedded inside it, CDLL returns the already-loaded image."""
    global _LIB
    if _LIB is None:
        path = os.path.join(os.path.dirname(__file__), "lib",
                            "libmxtpu_c_api.so")
        if not os.path.exists(path):
            raise MXNetError(
                "custom-op C tier: %s not built (tests build it via "
                "tests/test_c_api.py)" % path)
        lib = ctypes.CDLL(path)
        lib.MXGetLastError.restype = ctypes.c_char_p
        _LIB = lib
    return _LIB


def _check(rc):
    if rc != 0:
        raise MXNetError("custom-op C tier: %s"
                         % _lib().MXGetLastError().decode())


def _cb(cblist, idx, proto):
    if idx >= cblist.num_callbacks or not cblist.callbacks[idx]:
        return None, None
    fn = ctypes.cast(cblist.callbacks[idx], proto)
    return fn, cblist.contexts[idx]


def _copy_cblist(cblist):
    """Snapshot a caller-owned MXCallbackList (the struct and its arrays
    may be stack-allocated on the C side; the reference requires the
    arrays to outlive the op — copying removes even that footgun)."""
    out = MXCallbackList()
    n = cblist.num_callbacks
    out.num_callbacks = n
    cbs = (_GenericFunc * n)(*[cblist.callbacks[i] for i in range(n)])
    ctxs = (ctypes.c_void_p * n)(*[cblist.contexts[i] for i in range(n)])
    out.callbacks = ctypes.cast(cbs, ctypes.POINTER(_GenericFunc))
    out.contexts = ctypes.cast(ctxs, ctypes.POINTER(ctypes.c_void_p))
    out._keepalive = (cbs, ctxs)
    return out


# -- handle manufacture through the public ABI ------------------------------
def _new_handle(arr):
    """NDArrayHandle holding a copy of ``arr`` (numpy)."""
    lib = _lib()
    arr = np.ascontiguousarray(arr)
    h = ctypes.c_void_p()
    shape = (ctypes.c_uint * arr.ndim)(*arr.shape)
    tid = _DTYPE_TO_ID[arr.dtype.name]
    _check(lib.MXNDArrayCreateEx(shape, arr.ndim, 1, 0, 0, tid,
                                 ctypes.byref(h)))
    _check(lib.MXNDArraySyncCopyFromCPU(
        h, arr.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(arr.size)))
    return h


def _read_handle(h, shape, dtype):
    lib = _lib()
    out = np.empty(shape, dtype)
    _check(lib.MXNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.c_void_p), ctypes.c_size_t(out.size)))
    return out


def _free_handles(handles):
    lib = _lib()
    for h in handles:
        lib.MXNDArrayFree(h)


def _as_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else np.asarray(x)


# -- the prop adapter -------------------------------------------------------
def register_c_op(op_type, creator_addr):
    """MXCustomOpRegister: wrap a C CustomOpPropCreator as a Python
    CustomOpProp subclass and register it under ``op_type``."""
    from . import operator as _operator

    creator = ctypes.cast(ctypes.c_void_p(int(creator_addr)), PropCreator)

    class _CProp(_operator.CustomOpProp):
        _op_type = str(op_type)

        def __init__(self, **kwargs):
            super().__init__(need_top_grad=True)
            keys = [str(k).encode() for k in kwargs]
            vals = [str(v).encode() for v in kwargs.values()]
            ka = (ctypes.c_char_p * max(len(keys), 1))(*(keys or [None]))
            va = (ctypes.c_char_p * max(len(vals), 1))(*(vals or [None]))
            raw = MXCallbackList()
            if not creator(self._op_type.encode(), len(keys), ka, va,
                           ctypes.byref(raw)):
                raise MXNetError("custom op %r: C creator failed"
                                 % self._op_type)
            self._cblist = _copy_cblist(raw)

        # ---- metadata callbacks ----
        def _list(self, idx):
            fn, ctx = _cb(self._cblist, idx, ListFunc)
            if fn is None:
                return []
            out = ctypes.POINTER(ctypes.c_char_p)()
            if not fn(ctypes.byref(out), ctx):
                raise MXNetError("custom op %r: list callback failed"
                                 % self._op_type)
            res = []
            i = 0
            while out[i]:
                res.append(out[i].decode())
                i += 1
            return res

        def list_arguments(self):
            return self._list(K_PROP_LIST_ARGS) or ["data"]

        def list_outputs(self):
            return self._list(K_PROP_LIST_OUTS) or ["output"]

        def list_auxiliary_states(self):
            return self._list(K_PROP_LIST_AUX)

        def infer_shape(self, in_shape):
            fn, ctx = _cb(self._cblist, K_PROP_INFER_SHAPE, InferShapeFunc)
            if fn is None:
                return super().infer_shape(in_shape)
            n_in = len(in_shape)
            n_out = len(self.list_outputs())
            n_aux = len(self.list_auxiliary_states())
            total = n_in + n_out + n_aux
            ndims = (ctypes.c_int * total)(
                *([len(s) for s in in_shape] + [0] * (total - n_in)))
            bufs = [(ctypes.c_uint * max(len(s), 1))(*s) for s in in_shape]
            shapes = (_c_uint_p * total)()
            for i, b in enumerate(bufs):
                shapes[i] = ctypes.cast(b, _c_uint_p)
            if not fn(total, ndims, shapes, ctx):
                raise MXNetError("custom op %r: infer_shape failed"
                                 % self._op_type)
            all_shapes = [tuple(int(shapes[i][j]) for j in range(ndims[i]))
                          for i in range(total)]
            return (all_shapes[:n_in], all_shapes[n_in:n_in + n_out],
                    all_shapes[n_in + n_out:])

        def infer_type(self, in_type):
            fn, ctx = _cb(self._cblist, K_PROP_INFER_TYPE, InferTypeFunc)
            if fn is None:
                return super().infer_type(in_type)
            n_in = len(in_type)
            n_out = len(self.list_outputs())
            n_aux = len(self.list_auxiliary_states())
            total = n_in + n_out + n_aux
            types = (ctypes.c_int * total)(
                *([_DTYPE_TO_ID[np.dtype(t).name] for t in in_type]
                  + [-1] * (total - n_in)))
            if not fn(total, types, ctx):
                raise MXNetError("custom op %r: infer_type failed"
                                 % self._op_type)
            ids = [int(types[i]) for i in range(total)]
            conv = [_DTYPE_FROM_ID.get(i, np.float32) for i in ids]
            return (conv[:n_in], conv[n_in:n_in + n_out],
                    conv[n_in + n_out:])

        def declare_backward_dependency(self, out_grad, in_data, out_data):
            fn, ctx = _cb(self._cblist, K_PROP_BWD_DEP, BwdDepFunc)
            if fn is None:
                return super().declare_backward_dependency(
                    out_grad, in_data, out_data)
            og = (ctypes.c_int * max(len(out_grad), 1))(*(out_grad or [0]))
            ind = (ctypes.c_int * max(len(in_data), 1))(*(in_data or [0]))
            od = (ctypes.c_int * max(len(out_data), 1))(*(out_data or [0]))
            num = ctypes.c_int(0)
            rdeps = _c_int_p()
            if not fn(og, ind, od, ctypes.byref(num), ctypes.byref(rdeps),
                      ctx):
                raise MXNetError("custom op %r: backward-dependency "
                                 "callback failed" % self._op_type)
            return [int(rdeps[i]) for i in range(num.value)]

        def create_operator(self, ctx_str, in_shapes, in_dtypes=None):
            fn, cctx = _cb(self._cblist, K_PROP_CREATE_OP, CreateFunc)
            if fn is None:
                raise MXNetError("custom op %r: no create_operator "
                                 "callback" % self._op_type)
            n = len(in_shapes)
            if in_dtypes is None:
                in_dtypes = [np.float32] * n
            ndims = (ctypes.c_int * n)(*[len(s) for s in in_shapes])
            bufs = [(ctypes.c_uint * max(len(s), 1))(*s) for s in in_shapes]
            shapes = (_c_uint_p * n)()
            for i, b in enumerate(bufs):
                shapes[i] = ctypes.cast(b, _c_uint_p)
            dtypes = (ctypes.c_int * n)(
                *[_DTYPE_TO_ID[np.dtype(t).name] for t in in_dtypes])
            raw = MXCallbackList()
            if not fn(str(ctx_str).encode(), n, shapes, ndims, dtypes,
                      ctypes.byref(raw), cctx):
                raise MXNetError("custom op %r: create_operator failed"
                                 % self._op_type)
            return _COp(self._op_type, _copy_cblist(raw))

        def __del__(self):
            try:
                fn, ctx = _cb(self._cblist, K_PROP_DELETE, DelFunc)
                if fn is not None:
                    fn(ctx)
            except Exception:
                pass

    _CProp.__name__ = "CProp_%s" % op_type
    _operator.register(str(op_type))(_CProp)
    return True


class _COp:
    """Execution half: forwards/backwards through the C FB callbacks.

    Duck-typed against mxnet_tpu.operator.CustomOp — the custom_call
    bridge only needs forward/backward/assign."""

    def __init__(self, op_type, cblist):
        self._op_type = op_type
        self._cblist = cblist

    def assign(self, dst, req, src):
        from .operator import CustomOp

        CustomOp.assign(self, dst, req, src)

    def _invoke(self, idx, groups, reqs, is_train):
        """groups: list of (arrays, tag, writeback); flattens to the
        (ptrs, tags) ABI arrays, round-trips the data, frees handles."""
        fn, ctx = _cb(self._cblist, idx, FBFunc)
        if fn is None:
            raise MXNetError("custom op %r: missing %s callback"
                             % (self._op_type,
                                "forward" if idx == K_OP_FORWARD
                                else "backward"))
        ptrs, tags, slots = [], [], []
        for arrays, tag, writeback in groups:
            for a in arrays:
                npv = _as_numpy(a)
                h = _new_handle(npv)
                ptrs.append(h.value)
                tags.append(tag)
                slots.append((h, a, npv.shape, npv.dtype, writeback))
        size = len(ptrs)
        pa = (ctypes.c_void_p * max(size, 1))(*(ptrs or [None]))
        ta = (ctypes.c_int * max(size, 1))(*(tags or [0]))
        ra = (ctypes.c_int * max(len(reqs), 1))(
            *([_REQ_TO_INT.get(r, 1) for r in reqs] or [1]))
        ok = fn(size, pa, ta, ra, 1 if is_train else 0, ctx)
        results = []
        try:
            if not ok:
                raise MXNetError("custom op %r: C callback failed"
                                 % self._op_type)
            for h, a, shape, dtype, writeback in slots:
                if writeback:
                    results.append((a, _read_handle(h, shape, dtype)))
        finally:
            _free_handles([h for h, *_rest in slots])
        return results

    def forward(self, is_train, req, in_data, out_data, aux):
        groups = [(in_data, 0, False), (out_data, 1, True), (aux, 4, True)]
        updated = self._invoke(K_OP_FORWARD, groups, list(req), is_train)
        self._writeback(updated)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        groups = [(out_grad, 3, False), (in_data, 0, False),
                  (out_data, 1, False), (in_grad, 2, True), (aux, 4, True)]
        updated = self._invoke(K_OP_BACKWARD, groups, list(req), True)
        self._writeback(updated)

    @staticmethod
    def _writeback(updated):
        for target, value in updated:
            if hasattr(target, "_rebind"):
                from .ndarray.ndarray import array as _nd_array

                target[:] = _nd_array(value)
            else:
                target[:] = value

    def __del__(self):
        try:
            fn, ctx = _cb(self._cblist, K_OP_DELETE, DelFunc)
            if fn is not None:
                fn(ctx)
        except Exception:
            pass


# -- custom autograd function (MXCustomFunctionRecord) ----------------------
def record_custom_function(inputs, outputs, cblist_addr):
    """Splice a C backward into the autograd tape for imperatively
    computed outputs (ref: custom_function.cc CustomFunction)."""
    from . import autograd as ag

    raw = MXCallbackList.from_address(int(cblist_addr))
    cblist = _copy_cblist(raw)

    class _CFunction(ag.Function):
        def backward(self, *ograds):
            fn, ctx = _cb(cblist, K_FUNC_BACKWARD, FuncBwdFunc)
            if fn is None:
                raise MXNetError("custom function: no backward callback")
            og_np = [_as_numpy(g) for g in ograds]
            ig_np = [np.zeros(_as_numpy(i).shape, _as_numpy(i).dtype)
                     for i in inputs]
            handles = [_new_handle(a) for a in og_np + ig_np]
            try:
                pa = (ctypes.c_void_p * len(handles))(
                    *[h.value for h in handles])
                ra = (ctypes.c_int * max(len(ig_np), 1))(
                    *([1] * len(ig_np) or [1]))
                if not fn(len(og_np), len(ig_np), pa, ra, 1, ctx):
                    raise MXNetError("custom function: C backward failed")
                grads = [_read_handle(h, a.shape, a.dtype) for h, a in
                         zip(handles[len(og_np):], ig_np)]
            finally:
                _free_handles(handles)
            from .ndarray.ndarray import array as _nd_array

            return [_nd_array(g) for g in grads]

        def __del__(self):
            try:
                fn, ctx = _cb(cblist, K_FUNC_DELETE, DelFunc)
                if fn is not None:
                    fn(ctx)
            except Exception:
                pass

    f = _CFunction()
    if ag.is_recording():
        node = ag.record_op(None, {}, list(inputs), list(outputs),
                            [i._data() for i in inputs], custom=f)
        node.saved = [o._data() for o in outputs]
    return True
