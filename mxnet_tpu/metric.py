"""Evaluation metrics.

Reference counterpart: ``python/mxnet/metric.py`` (1,199 LoC): EvalMetric
base + registry (create), CompositeEvalMetric, Accuracy/TopK/F1/Perplexity/
MAE/MSE/RMSE/CrossEntropy/NLL/PearsonCorrelation/Loss/Torch/Caffe/
CustomMetric/np wrapper.
"""
from __future__ import annotations

import math

import numpy

from .base import MXNetError

_METRIC_REGISTRY = {}


def register(*names):
    def deco(klass):
        for n in names or (klass.__name__.lower(),):
            _METRIC_REGISTRY[n] = klass
        return klass

    return deco


def _materialize_dicts(label, pred):
    """ONE batched ``jax.device_get`` covering every device-backed array
    in both name->array dicts (ISSUE 5 satellite). The per-array
    ``asnumpy`` calls inside ``update()`` are each a blocking D2H round
    trip; fetching the whole tree at once overlaps the transfers and
    syncs a single time. Host numpy values pass through untouched."""
    keys, vals = [], []
    for which, d in (("l", label), ("p", pred)):
        for k, v in d.items():
            data = v._data() if hasattr(v, "_data") else v
            if type(data).__module__.startswith("jax"):
                keys.append((which, k))
                vals.append(data)
    if not vals:
        return label, pred
    import jax

    host = jax.device_get(vals)
    label, pred = dict(label), dict(pred)
    for (which, k), h in zip(keys, host):
        (label if which == "l" else pred)[k] = h
    return label, pred


def check_label_shapes(labels, preds, shape=False):
    if shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape[0], preds.shape[0]
    if label_shape != pred_shape:
        raise MXNetError(
            "Shape of labels %d does not match shape of predictions %d" % (label_shape, pred_shape)
        )


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = self._kwargs.copy()
        config.update(
            {"metric": self.__class__.__name__, "name": self.name,
             "output_names": self.output_names, "label_names": self.label_names}
        )
        return config

    def update_dict(self, label, pred):
        label, pred = _materialize_dicts(label, pred)
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError()

    # -- device-resident statistics (ISSUE 5) --------------------------------
    def _attach_device_source(self, source):
        """Register a device accumulator (FusedSPMDGroup's device-metric
        path). Its (sum, count) stays on device until :meth:`get` folds
        it in — the ONE host sync per Speedometer/epoch interval."""
        srcs = self.__dict__.setdefault("_device_sources", [])
        if source not in srcs:
            srcs.append(source)

    def _fold_device_sources(self):
        for src in self.__dict__.get("_device_sources", ()):
            s, n = src.drain()
            if n:
                self.sum_metric += s
                self.num_inst += n

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        for src in self.__dict__.get("_device_sources", ()):
            src.clear()

    def get(self):
        self._fold_device_sources()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        try:
            return self.metrics[index]
        except IndexError:
            return ValueError("Metric index {} is out of range 0 and {}".format(index, len(self.metrics)))

    def update_dict(self, labels, preds):
        # materialize ONCE for all children (their own update_dict then
        # sees host numpy and skips the device_get)
        labels, preds = _materialize_dicts(labels, preds)
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int, numpy.generic)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


def _as_numpy(x):
    return x.asnumpy() if hasattr(x, "asnumpy") else numpy.asarray(x)


@register("acc", "accuracy")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, axis=axis, output_names=output_names, label_names=label_names)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred_label in zip(labels, preds):
            label, pred_label = _as_numpy(label), _as_numpy(pred_label)
            if pred_label.shape != label.shape:
                pred_label = numpy.argmax(pred_label, axis=self.axis)
            pred_label = pred_label.astype("int32").ravel()
            label = label.astype("int32").ravel()
            check_label_shapes(label, pred_label)
            self.sum_metric += (pred_label == label).sum()
            self.num_inst += len(pred_label)


@register("top_k_accuracy", "top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None, label_names=None):
        super().__init__(name, top_k=top_k, output_names=output_names, label_names=label_names)
        self.top_k = top_k
        if self.top_k <= 1:
            raise MXNetError("Please use Accuracy if top_k is no more than 1")
        self.name += "_%d" % self.top_k

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred_label in zip(labels, preds):
            label, pred_label = _as_numpy(label), _as_numpy(pred_label)
            if len(pred_label.shape) > 2:
                pred_label = pred_label.reshape(pred_label.shape[0], -1)
            pred_label = numpy.argsort(pred_label.astype("float32"), axis=1)
            label = label.astype("int32").ravel()
            check_label_shapes(label, pred_label)
            num_samples = pred_label.shape[0]
            num_dims = len(pred_label.shape)
            if num_dims == 1:
                self.sum_metric += (pred_label.ravel() == label).sum()
            elif num_dims == 2:
                num_classes = pred_label.shape[1]
                top_k = min(num_classes, self.top_k)
                for j in range(top_k):
                    self.sum_metric += (pred_label[:, num_classes - 1 - j].ravel() == label).sum()
            self.num_inst += num_samples


@register("f1")
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None, average="macro"):
        super().__init__(name, output_names=output_names, label_names=label_names)
        self.average = average
        self.metrics = _BinaryClassMetrics()

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            self.metrics.update_binary_stats(_as_numpy(label), _as_numpy(pred))
        if self.average == "macro":
            self.sum_metric += self.metrics.fscore
            self.num_inst += 1
            self.metrics.reset_stats()
        else:
            self.sum_metric = self.metrics.fscore * self.metrics.total_examples
            self.num_inst = self.metrics.total_examples

    def reset(self):
        self.sum_metric = 0.0
        self.num_inst = 0
        if hasattr(self, "metrics"):
            self.metrics.reset_stats()


class _BinaryClassMetrics:
    def __init__(self):
        self.reset_stats()

    def reset_stats(self):
        self.true_positives = 0
        self.false_positives = 0
        self.true_negatives = 0
        self.false_negatives = 0

    def update_binary_stats(self, label, pred):
        pred_label = numpy.argmax(pred, axis=1)
        check_label_shapes(label, pred)
        if len(numpy.unique(label)) > 2:
            raise MXNetError("%s currently only supports binary classification." % self.__class__.__name__)
        for y_pred, y_true in zip(pred_label.ravel(), label.ravel()):
            if y_pred == 1 and y_true == 1:
                self.true_positives += 1
            elif y_pred == 1 and y_true == 0:
                self.false_positives += 1
            elif y_pred == 0 and y_true == 1:
                self.false_negatives += 1
            else:
                self.true_negatives += 1

    @property
    def precision(self):
        tot = self.true_positives + self.false_positives
        return self.true_positives / tot if tot > 0 else 0.0

    @property
    def recall(self):
        tot = self.true_positives + self.false_negatives
        return self.true_positives / tot if tot > 0 else 0.0

    @property
    def fscore(self):
        if self.precision + self.recall > 0:
            return 2 * self.precision * self.recall / (self.precision + self.recall)
        return 0.0

    @property
    def total_examples(self):
        return (self.false_negatives + self.false_positives + self.true_negatives + self.true_positives)


@register("perplexity")
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", output_names=None, label_names=None):
        super().__init__(name, ignore_label=ignore_label, axis=axis,
                         output_names=output_names, label_names=label_names)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            label = label.reshape(-1).astype("int32")
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[numpy.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label).astype(probs.dtype)
                num -= int(ignore.sum())
                probs = probs * (1 - ignore) + ignore
            loss -= numpy.sum(numpy.log(numpy.maximum(1e-10, probs)))
            num += probs.shape[0]
        self.sum_metric += numpy.exp(loss / num) * num if num > 0 else 0.0
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)


@register("mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.abs(label - pred).mean()
            self.num_inst += 1


@register("mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register("rmse")
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += numpy.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@register("ce", "cross-entropy")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None, label_names=None):
        super().__init__(name, eps=eps, output_names=output_names, label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[numpy.arange(label.shape[0]), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register("nll_loss")
class NegativeLogLikelihood(EvalMetric):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None, label_names=None):
        super().__init__(name, eps=eps, output_names=output_names, label_names=label_names)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            label = label.ravel()
            num_examples = pred.shape[0]
            assert label.shape[0] == num_examples, (label.shape[0], num_examples)
            prob = pred[numpy.arange(num_examples, dtype=numpy.int64), numpy.int64(label)]
            self.sum_metric += (-numpy.log(prob + self.eps)).sum()
            self.num_inst += num_examples


@register("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_numpy(label), _as_numpy(pred)
            check_label_shapes(label, pred)
            self.sum_metric += numpy.corrcoef(pred.ravel(), label.ravel())[0, 1]
            self.num_inst += 1


@register("loss")
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names=output_names, label_names=label_names)

    def update(self, _, preds):
        for pred in preds:
            loss = _as_numpy(pred).sum()
            self.sum_metric += loss
            self.num_inst += _as_numpy(pred).size


@register("custom")
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False, output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, feval=feval, allow_extra_outputs=allow_extra_outputs,
                         output_names=output_names, label_names=label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds, shape=True)
        for pred, label in zip(preds, labels):
            label, pred = _as_numpy(label), _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    if isinstance(metric, str):
        klass = _METRIC_REGISTRY.get(metric.lower())
        if klass is None:
            raise MXNetError("unknown metric %r" % metric)
        return klass(*args, **kwargs)
    raise MXNetError("cannot create metric from %r" % (metric,))
