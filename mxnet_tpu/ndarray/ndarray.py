"""NDArray — the imperative tensor value type, plus the op-invoke machinery.

Reference counterpart: ``include/mxnet/ndarray.h:79-921`` +
``python/mxnet/ndarray/ndarray.py``. TPU-native design: an NDArray is a
mutable *handle* over an immutable ``jax.Array``. The reference's
Chunk{Storage::Handle, Engine::Var} pair collapses to the jax buffer itself:
XLA's async dispatch provides the ThreadedEngine's read/write ordering, and
``WaitToRead`` becomes ``block_until_ready``. In-place ops rebind the
handle; views (slices) write through to their parent via lazy index update
(the copy-on-write discipline SURVEY §7 'hard parts' calls for).
"""
from __future__ import annotations

import numpy as _np

from .. import autograd as _ag
from .. import random as _random
from ..base import MXNetError, dtype_name, dtype_np
from ..context import Context, cpu, current_context
from ..ops import registry as _reg

__all__ = [
    "NDArray",
    "array",
    "empty",
    "zeros",
    "ones",
    "full",
    "arange",
    "concatenate",
    "moveaxis",
    "onehot_encode",
    "imdecode",
    "waitall",
    "invoke",
]


def _is_tensor_like(v):
    return isinstance(v, (NDArray, _np.ndarray)) or type(v).__module__.startswith("jax")


class NDArray:
    """Multi-dimensional array on a device context."""

    __slots__ = ("_jax", "_ctx", "_grad_entry", "_base", "_index", "_stype",
                 "_view_cache", "__weakref__")

    # numpy should defer binary ops to us
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None, base=None, index=None, stype="default"):
        self._jax = data  # jax.Array | None (when view)
        self._ctx = ctx or current_context()
        self._grad_entry = None
        self._base = base  # parent NDArray when this is a view
        self._index = index  # index into parent
        self._stype = stype
        self._view_cache = None  # (base buffer, sliced value) memo

    # -- raw value access ----------------------------------------------------
    def _data(self):
        if self._base is not None:
            # memoize the computed slice per base buffer: every property
            # read (shape/dtype) goes through _data(), and zero-copy
            # iterator batches (NDArrayIter fast path) are views read
            # many times per batch — without the memo each read would
            # dispatch a fresh slice op
            base = self._base._data()
            cached = self._view_cache
            if cached is not None and cached[0] is base:
                return cached[1]
            value = base[self._index]
            self._view_cache = (base, value)
            return value
        return self._jax

    def _rebind(self, new_value):
        """Point this handle at a new device buffer (in-place op semantics).

        If this array is a view, write through to the parent (the reference's
        shared-Chunk behavior, ndarray.h:635-875).
        """
        if self._base is not None:
            self._base._rebind(self._base._data().at[self._index].set(new_value))
        else:
            self._jax = new_value

    # -- basic properties ----------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data().shape)

    @property
    def ndim(self):
        return self._data().ndim

    @property
    def size(self):
        return int(self._data().size)

    @property
    def dtype(self):
        d = self._data().dtype
        return d.type if hasattr(d, "type") else d

    @property
    def stype(self):
        return self._stype

    @property
    def context(self):
        return self._ctx

    @property
    def ctx(self):
        return self._ctx

    @property
    def grad(self):
        e = self._grad_entry
        return e.grad if e is not None else None

    @property
    def handle(self):
        return self  # parity shim: some code passes .handle around

    # -- sync points (ref: NDArray::WaitToRead / Engine::WaitForAll) ---------
    def wait_to_read(self):
        self._data().block_until_ready()

    def wait_to_write(self):
        self._data().block_until_ready()

    def asnumpy(self):
        return _np.asarray(self._data())

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __repr__(self):
        return "\n%s\n<%s %s @%s>" % (
            _np.asarray(self._data()),
            type(self).__name__,
            "x".join(str(s) for s in self.shape),
            self._ctx,
        )

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    # -- dtype / context movement --------------------------------------------
    def astype(self, dtype, copy=True):
        if dtype_name(self.dtype) == dtype_name(dtype) and not copy:
            return self
        return invoke("Cast", [self], {"dtype": dtype_name(dtype_np(dtype))})

    def copy(self):
        return invoke("_copy", [self], {})

    def copyto(self, other):
        """Copy into another NDArray or to a context (ref: CopyFromTo)."""
        import jax

        if isinstance(other, Context):
            arr = jax.device_put(self._data(), Context(other).jax_device())
            return NDArray(arr, ctx=Context(other))
        if isinstance(other, NDArray):
            val = jax.device_put(self._data(), other._ctx.jax_device())
            if val.dtype != other._data().dtype:
                val = val.astype(other._data().dtype)
            other._rebind(val.reshape(other.shape))
            return other
        raise MXNetError("copyto: unsupported target %r" % (other,))

    def as_in_context(self, context):
        if context == self._ctx:
            return self
        return self.copyto(context)

    def detach(self):
        out = NDArray(self._data(), ctx=self._ctx)
        return out

    def attach_grad(self, grad_req="write", stype=None):
        """Allocate grad buffer & mark as autograd variable (gluon surface)."""
        grad = zeros(self.shape, ctx=self._ctx, dtype=self.dtype)
        _ag.mark_variables([self], [grad], grad_reqs=grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _ag.backward([self], [out_grad] if out_grad is not None else None,
                     retain_graph=retain_graph, train_mode=train_mode)

    # -- indexing ------------------------------------------------------------
    def __getitem__(self, key):
        key = self._norm_key(key)
        if isinstance(key, NDArray):
            return invoke("take", [self, key], {"axis": 0, "mode": "clip"})
        if self._base is not None:
            composed = self._chain_index(key)
            if composed is None:
                # the key has no single-root-index form (tuple/fancy
                # keys, or a view over one): read out of the
                # materialized view instead — writes to the result do
                # not flow back to the root, same as take() copies
                return NDArray(self._data()[key], ctx=self._ctx)
            return NDArray(None, ctx=self._ctx, base=self._root(),
                           index=composed)
        # return a view that writes through on _rebind
        return NDArray(None, ctx=self._ctx, base=self._root(), index=key)

    def _root(self):
        return self._base if self._base is not None else self

    def _chain_index(self, key):
        """Compose a key applied to this view into one root index, or
        None when the composition has no single-index form (tuple and
        fancy keys). Slice-of-slice (any step/sign) and integer keys
        stay zero-copy write-through views — the batch-feed path
        slices iterator views again per device
        (executor_group._load_slice on NDArrayIter's zero-copy batches)
        and must not force a copy, and a detached copy would silently
        break the write-through contract single-level views have."""
        idx = self._index
        if not isinstance(idx, slice):
            return None  # view over an int/fancy key: row has no axis 0
        rows = range(*idx.indices(self._base._data().shape[0]))
        if isinstance(key, (int, _np.integer)) and not isinstance(key, bool):
            return rows[int(key)]  # IndexError out of range, as numpy
        if isinstance(key, slice):
            r = rows[key]
            # a negative normalized stop only happens stepping downward
            # past row 0, where the sentinel is None
            return slice(r.start, r.stop if r.stop >= 0 else None, r.step)
        return None

    def _norm_key(self, key):
        if isinstance(key, NDArray) and key.dtype != _np.bool_:
            return key
        if isinstance(key, _np.ndarray):
            return array(key, ctx=self._ctx)
        return key

    def __setitem__(self, key, value):
        import jax.numpy as jnp

        data = self._data()
        if isinstance(key, slice) and key.start is None and key.stop is None and key.step is None:
            # a[:] = v  — full overwrite
            self._rebind(self._coerce_value(value, data.shape, data.dtype))
            return
        if isinstance(key, NDArray):
            key = key._data()
        val = value._data() if isinstance(value, NDArray) else value
        if isinstance(val, (int, float)):
            self._rebind(data.at[key].set(val))
        else:
            val = jnp.asarray(val, dtype=data.dtype)
            self._rebind(data.at[key].set(val))

    def _coerce_value(self, value, shape, dtype):
        import jax.numpy as jnp

        if isinstance(value, NDArray):
            v = value._data()
        elif isinstance(value, (int, float)):
            return jnp.full(shape, value, dtype=dtype)
        else:
            v = jnp.asarray(value)
        v = v.astype(dtype) if v.dtype != dtype else v
        return jnp.broadcast_to(v, shape) if v.shape != tuple(shape) else v.reshape(shape)

    # -- shape ops (fluent methods, ref: ndarray.py fluent section) ----------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape"):
            shape = tuple(kwargs["shape"])
        return invoke("Reshape", [self], {"shape": shape, "reverse": kwargs.get("reverse", False)})

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def broadcast_to(self, shape):
        return invoke("broadcast_to", [self], {"shape": tuple(shape)})

    def broadcast_like(self, other):
        return invoke("broadcast_like", [self, other], {})

    @property
    def T(self):
        return invoke("transpose", [self], {})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return invoke("transpose", [self], {"axes": axes})

    def expand_dims(self, axis):
        return invoke("expand_dims", [self], {"axis": axis})

    def flatten(self):
        return invoke("Flatten", [self], {})

    def squeeze(self, axis=None):
        return invoke("squeeze", [self], {"axis": axis})

    def swapaxes(self, dim1, dim2):
        return invoke("swapaxes", [self], {"dim1": dim1, "dim2": dim2})

    def flip(self, axis):
        return invoke("flip", [self], {"axis": axis})

    def tile(self, reps):
        return invoke("tile", [self], {"reps": reps})

    def repeat(self, repeats, axis=None):
        return invoke("repeat", [self], {"repeats": repeats, "axis": axis})

    def pad(self, mode, pad_width, constant_value=0.0):
        return invoke("pad", [self], {"mode": mode, "pad_width": pad_width, "constant_value": constant_value})

    def slice(self, begin, end, step=()):
        return invoke("slice", [self], {"begin": begin, "end": end, "step": step})

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", [self], {"axis": axis, "begin": begin, "end": end})

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", [self, indices], {"axis": axis, "mode": mode})

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        return invoke("one_hot", [self], {"depth": depth, "on_value": on_value, "off_value": off_value, "dtype": dtype})

    def pick(self, index, axis=-1, keepdims=False):
        return invoke("pick", [self, index], {"axis": axis, "keepdims": keepdims})

    # -- reductions ----------------------------------------------------------
    def sum(self, axis=None, keepdims=False):
        return invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def nansum(self, axis=None, keepdims=False):
        return invoke("nansum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False):
        return invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False):
        return invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke("norm", [self], {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return invoke("argsort", [self], {"axis": axis, "is_ascend": is_ascend})

    def sort(self, axis=-1, is_ascend=True):
        return invoke("sort", [self], {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return invoke("topk", [self], {"axis": axis, "k": k, "ret_typ": ret_typ, "is_ascend": is_ascend})

    # -- elementwise fluent --------------------------------------------------
    def abs(self):
        return invoke("abs", [self], {})

    def sign(self):
        return invoke("sign", [self], {})

    def square(self):
        return invoke("square", [self], {})

    def sqrt(self):
        return invoke("sqrt", [self], {})

    def exp(self):
        return invoke("exp", [self], {})

    def log(self):
        return invoke("log", [self], {})

    def relu(self):
        return invoke("relu", [self], {})

    def sigmoid(self):
        return invoke("sigmoid", [self], {})

    def tanh(self):
        return invoke("tanh", [self], {})

    def softmax(self, axis=-1):
        return invoke("softmax", [self], {"axis": axis})

    def log_softmax(self, axis=-1):
        return invoke("log_softmax", [self], {"axis": axis})

    def clip(self, a_min, a_max):
        return invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def round(self):
        return invoke("round", [self], {})

    def rint(self):
        return invoke("rint", [self], {})

    def floor(self):
        return invoke("floor", [self], {})

    def ceil(self):
        return invoke("ceil", [self], {})

    def trunc(self):
        return invoke("trunc", [self], {})

    def dot(self, other, transpose_a=False, transpose_b=False):
        from . import sparse as _sp

        if isinstance(self, _sp.CSRNDArray) and not transpose_b:
            # sparse segment-sum kernel, not the dense fallback
            return _sp.dot(self, other, transpose_a=transpose_a)
        return invoke("dot", [self, other], {"transpose_a": transpose_a, "transpose_b": transpose_b})

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp

        return _sp.cast_storage(self, stype)

    def as_np_ndarray(self):
        return self.asnumpy()

    # -- arithmetic dunders --------------------------------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            args = [other, self] if reverse else [self, other]
            return invoke(op, args, {})
        if isinstance(other, (int, float, _np.generic)):
            return invoke(scalar_op, [self], {"scalar": float(other)})
        if isinstance(other, _np.ndarray):
            o = array(other, ctx=self._ctx)
            args = [o, self] if reverse else [self, o]
            return invoke(op, args, {})
        return NotImplemented

    def __add__(self, other):
        return self._binop(other, "broadcast_add", "_plus_scalar")

    def __radd__(self, other):
        return self._binop(other, "broadcast_add", "_plus_scalar")

    def __sub__(self, other):
        return self._binop(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        return self._binop(other, "broadcast_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, other):
        return self._binop(other, "broadcast_mul", "_mul_scalar")

    def __rmul__(self, other):
        return self._binop(other, "broadcast_mul", "_mul_scalar")

    def __truediv__(self, other):
        return self._binop(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        return self._binop(other, "broadcast_div", "_rdiv_scalar", reverse=True)

    def __div__(self, other):
        return self.__truediv__(other)

    def __mod__(self, other):
        return self._binop(other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        return self._binop(other, "broadcast_mod", "_rmod_scalar", reverse=True)

    def __pow__(self, other):
        return self._binop(other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        return self._binop(other, "broadcast_power", "_rpower_scalar", reverse=True)

    def __neg__(self):
        return invoke("negative", [self], {})

    def __abs__(self):
        return invoke("abs", [self], {})

    def __eq__(self, other):
        if other is None:
            return False
        return self._binop(other, "broadcast_equal", "_equal_scalar")

    def __ne__(self, other):
        if other is None:
            return True
        return self._binop(other, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, other):
        return self._binop(other, "broadcast_greater", "_greater_scalar")

    def __ge__(self, other):
        return self._binop(other, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, other):
        return self._binop(other, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, other):
        return self._binop(other, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __iadd__(self, other):
        res = self.__add__(other)
        self._rebind(res._data())
        return self

    def __isub__(self, other):
        res = self.__sub__(other)
        self._rebind(res._data())
        return self

    def __imul__(self, other):
        res = self.__mul__(other)
        self._rebind(res._data())
        return self

    def __itruediv__(self, other):
        res = self.__truediv__(other)
        self._rebind(res._data())
        return self

    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx": (self._ctx.device_type, self._ctx.device_id)}

    def __setstate__(self, state):
        import jax

        ctx = Context(state["ctx"][0], state["ctx"][1])
        self._jax = jax.device_put(state["data"], ctx.jax_device())
        self._ctx = ctx
        self._grad_entry = None
        self._base = None
        self._index = None
        self._stype = "default"
        self._view_cache = None


# ---------------------------------------------------------------------------
# op invocation (the MXImperativeInvoke analogue, ref c_api_ndarray.cc:117)
# ---------------------------------------------------------------------------
_STATEFUL_POST = {}


def register_stateful_post(op_name):
    def deco(fn):
        _STATEFUL_POST[op_name] = fn
        return fn

    return deco


_SYMBOL_CLS = None


def invoke(op, inputs, attrs, out=None, ctx=None):
    """Invoke a registered op on NDArrays.

    Pipeline (mirrors Imperative::Invoke, src/imperative/imperative.cc:37-110):
    coerce attrs → thread PRNG key if needed → apply kernel via XLA →
    wrap outputs → rebind mutated inputs → record on autograd tape.
    """
    if isinstance(op, str):
        op = _reg.get(op)
    inputs = [x for x in inputs]
    # symbolic tracing (HybridBlock.export): any Symbol input composes a
    # graph node instead of executing — the layer code is F-agnostic
    global _SYMBOL_CLS
    if _SYMBOL_CLS is None:
        from ..symbol.symbol import Symbol as _SYMBOL_CLS_  # noqa: N806

        _SYMBOL_CLS = _SYMBOL_CLS_
    _Sym = _SYMBOL_CLS

    if any(isinstance(x, _Sym) for x in inputs):
        from ..symbol.register import create_symbol

        bad = [x for x in inputs if x is not None and not isinstance(x, _Sym)]
        if bad:
            raise MXNetError(
                "op %s: cannot mix NDArray and Symbol inputs during "
                "symbolic tracing" % op.name)
        sattrs = {k: v for k, v in attrs.items() if v is not None}
        sattrs.pop("name", None)
        sattrs.pop("ctx", None)
        return create_symbol(op, inputs, sattrs)
    ctx = ctx or (inputs[0]._ctx if inputs else None) or current_context()

    attrs = {k: v for k, v in attrs.items() if v is not None or k in ("axis", "dtype")}
    attrs.pop("name", None)
    attrs.pop("ctx", None) if "ctx" not in op.attr_defaults else None
    parsed = op.parse_attrs(attrs)
    if "__is_train__" in op.attr_defaults:
        parsed["__is_train__"] = _ag.is_training()

    raw = [x._data() if isinstance(x, NDArray) else x for x in inputs]
    key = _random.next_key(ctx) if op.needs_rng else None
    arrays = ([key] + raw) if op.needs_rng else raw

    from .. import profiler as _prof

    # kAllOperator mode: stamp every imperative dispatch (ref: profiler
    # modes, src/engine/profiler.h:97-98)
    with _prof.maybe_scope(op.name, "operator", mode="all"):
        results = (_reg.apply_op_with_key(op, arrays, parsed)
                   if op.needs_rng else _reg.apply_op(op, raw, parsed))
    if not isinstance(results, tuple):
        results = (results,)

    n_vis = op.n_visible_outputs(parsed)

    # mutated inputs: rebind handles (optimizer update ops)
    if op.mutate_inputs:
        for out_idx, in_idx in enumerate(op.mutate_inputs):
            if in_idx < len(inputs) and out_idx < len(results) and isinstance(inputs[in_idx], NDArray):
                if op.name != "BatchNorm":
                    inputs[in_idx]._rebind(results[out_idx])

    post = _STATEFUL_POST.get(op.name)
    if post is not None:
        post(inputs, results, parsed)

    outputs = [NDArray(r, ctx=ctx) for r in results[:n_vis]]

    if out is not None:
        outs = [out] if isinstance(out, NDArray) else list(out)
        for o, r in zip(outs, results[:n_vis]):
            o._rebind(r if r.dtype == o._data().dtype else r.astype(o._data().dtype))
        outputs = outs

    if _ag.is_recording() and not op.nondiff:
        _ag.record_op(op, parsed, inputs, outputs, raw, rng_key=key)

    return outputs[0] if n_vis == 1 else outputs


@register_stateful_post("BatchNorm")
def _bn_post(inputs, results, attrs):
    """Moving-stat update: moving = momentum*moving + (1-m)*batch
    (ref: src/operator/nn/batch_norm.cc aux-state mutation)."""
    if not attrs.get("__is_train__") or attrs.get("use_global_stats"):
        return
    momentum = attrs.get("momentum", 0.9)
    _, mean, var = results[:3]
    mm, mv = inputs[3], inputs[4]
    if isinstance(mm, NDArray):
        mm._rebind(momentum * mm._data() + (1 - momentum) * mean)
    if isinstance(mv, NDArray):
        mv._rebind(momentum * mv._data() + (1 - momentum) * var)


def _wrap_raw(raw, ctx=None):
    return NDArray(raw, ctx=ctx or current_context())


def _wrap_result(res, ctx=None):
    if isinstance(res, tuple):
        return [_wrap_raw(r, ctx) for r in res]
    return _wrap_raw(res, ctx)


# ---------------------------------------------------------------------------
# creation functions (ref: python/mxnet/ndarray/utils.py + ndarray.py)
# ---------------------------------------------------------------------------
def array(source_array, ctx=None, dtype=None):
    import jax

    ctx = ctx or current_context()
    was_ndarray = isinstance(source_array, (_np.ndarray, NDArray)) or (
        type(source_array).__module__.startswith("jax")
    )
    if isinstance(source_array, NDArray):
        source_array = source_array.asnumpy()
    np_arr = _np.asarray(source_array)
    if dtype is None:
        # parity: python lists default to float32; numpy arrays keep their
        # dtype (except float64 → float32, the framework default precision)
        if not was_ndarray or np_arr.dtype == _np.float64:
            dtype = _np.float32
        else:
            dtype = np_arr.dtype
    np_arr = np_arr.astype(dtype_np(dtype)) if dtype_name(np_arr.dtype) != dtype_name(dtype) else np_arr
    return NDArray(jax.device_put(np_arr, ctx.jax_device()), ctx=ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, stype=None, **kwargs):
    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return invoke("_zeros", [], {"shape": shape, "dtype": dtype_name(dtype_np(dtype))}, ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return invoke("_ones", [], {"shape": shape, "dtype": dtype_name(dtype_np(dtype))}, ctx=ctx)


def full(shape, val, ctx=None, dtype=None, out=None):
    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return invoke("_full", [], {"shape": shape, "value": val, "dtype": dtype_name(dtype_np(dtype))}, out=out, ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    ctx = ctx or current_context()
    return invoke(
        "_arange",
        [],
        {"start": start, "stop": stop, "step": step, "repeat": repeat, "dtype": dtype_name(dtype_np(dtype))},
        ctx=ctx,
    )


def concatenate(arrays, axis=0, always_copy=True):
    return invoke("Concat", list(arrays), {"dim": axis})


def moveaxis(tensor, source, destination):
    import jax.numpy as jnp

    return _wrap_raw(jnp.moveaxis(tensor._data(), source, destination), tensor._ctx)


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = invoke("one_hot", [indices], {"depth": depth})
    out._rebind(res._data().astype(out._data().dtype))
    return out


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0, channels=3, mean=None):
    raise MXNetError("imdecode: use mxnet_tpu.image instead")


def waitall():
    """Block until all async computation completes (ref: Engine::WaitForAll)."""
    import jax

    (jax.device_put(0.0) + 0).block_until_ready()


def load(fname):
    from .utils import load as _load

    return _load(fname)


def save(fname, data):
    from .utils import save as _save

    return _save(fname, data)
