"""Autogeneration of the ``mx.nd.*`` operator namespace from the registry.

Reference counterpart: ``python/mxnet/ndarray/register.py:29-156`` +
``base.py:452-584`` (_init_op_module enumerating C-registered ops and
code-generating python wrappers). Here the registry is in-process, so
"generation" is building closures; namespaces (``_contrib_``, ``_linalg_``,
``_random_``/``_sample_``) land in submodule objects exactly like the
reference's ``mx.nd.contrib``/``linalg``/``random``.
"""
from __future__ import annotations

import types

from ..base import MXNetError
from ..ops import registry as _reg
from .ndarray import NDArray, invoke


def _tensor_like(v):
    import numpy as _np

    return isinstance(v, NDArray) or isinstance(v, _np.ndarray) or (
        type(v).__module__.startswith("jax")
    )


def _make_op_func(op):
    input_names = op.input_names
    var_inputs = op.var_inputs

    def generic_op(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        if var_inputs:
            # kwarg tensors follow positionals; when the op defines an
            # input order (Custom: prop.list_arguments()), named tensors
            # bind by NAME, not kwarg insertion order
            tensors = [a for a in args if isinstance(a, NDArray)]
            named = {k: v for k, v in kwargs.items() if isinstance(v, NDArray)}
            attrs = {k: v for k, v in kwargs.items() if not isinstance(v, NDArray)}
            attrs.pop("num_args", None)
            if named and op.kwarg_input_order is not None:
                order = op.kwarg_input_order(attrs)
                unknown = set(named) - set(order)
                if unknown:
                    raise MXNetError(
                        "op %s: tensor kwargs %s not in its argument "
                        "list %s" % (op.name, sorted(unknown), order))
                tensors += [named[k] for k in order if k in named]
            else:
                tensors += list(named.values())
        else:
            # merge positional + named tensors into signature order; scalar
            # positionals map onto attr slots in signature order (parity with
            # generated-code signatures like random.uniform(low, high, shape))
            slots = {}
            attrs = {}
            for k, v in kwargs.items():
                if k in input_names:
                    slots[k] = v
                else:
                    attrs[k] = v
            pos_tensors = [a for a in args if _tensor_like(a)]
            pos_scalars = [a for a in args if not _tensor_like(a)]
            tensors = []
            qi = 0
            for nm in input_names:
                if nm in slots:
                    tensors.append(slots[nm])
                elif qi < len(pos_tensors):
                    tensors.append(pos_tensors[qi])
                    qi += 1
                else:
                    tensors.append(None)
            if qi < len(pos_tensors):
                raise MXNetError(
                    "op %s: too many positional tensor args (%d given, takes %d)"
                    % (op.name, len(pos_tensors), len(input_names))
                )
            if pos_scalars:
                attr_order = list(op.attr_defaults.keys())
                si = 0
                for val in pos_scalars:
                    while si < len(attr_order) and attr_order[si] in attrs:
                        si += 1
                    if si >= len(attr_order):
                        raise MXNetError("op %s: too many positional args" % op.name)
                    attrs[attr_order[si]] = val
                    si += 1
            # drop trailing missing optionals
            while tensors and tensors[-1] is None:
                tensors.pop()
        return invoke(op, tensors, attrs, out=out)

    generic_op.__name__ = op.name
    generic_op.__doc__ = op.doc
    return generic_op


def populate_module(mod, symbolic=False):
    """Install every registered op (and alias) as a function on `mod`.

    Namespace routing mirrors the reference: ops named ``_contrib_X`` go to
    ``mod.contrib.X``, ``_linalg_X`` → ``mod.linalg.X``, ``_random_X`` and
    ``_sample_X`` → ``mod.random``; everything else lands on ``mod`` (public
    if no leading underscore, internal otherwise — internal ops still
    installed, as ``mx.nd._internal`` does).
    """
    from ..symbol.register import make_symbol_func

    maker = make_symbol_func if symbolic else _make_op_func
    sub = {}
    for ns in ("contrib", "linalg", "random", "sparse", "image"):
        m = getattr(mod, ns, None)
        if m is None:
            m = types.ModuleType(mod.__name__ + "." + ns)
            setattr(mod, ns, m)
        sub[ns] = m

    for name in _reg.list_ops():
        op = _reg.get(name)
        fn = maker(op)
        fn.__name__ = name
        target, public = _route(name)
        if target is None:
            setattr(mod, name, fn)
            if name.startswith("_"):
                continue
        else:
            setattr(sub[target], public, fn)
            # reference also exposes e.g. mx.nd._sample_uniform
            setattr(mod, name, fn)

    # mx.nd.random.X dispatches scalar params → _random_X, tensor params →
    # _sample_X (parity: python/mxnet/ndarray/random.py _random_helper)
    for dist in ("uniform", "normal", "gamma", "exponential", "poisson",
                 "negative_binomial", "generalized_negative_binomial"):
        rand_name = "_random_" + dist
        samp_name = "_sample_" + dist
        if not (_reg.exists(rand_name) and _reg.exists(samp_name)):
            continue
        rand_fn = maker(_reg.get(rand_name))
        samp_fn = maker(_reg.get(samp_name))

        def dispatcher(*args, _r=rand_fn, _s=samp_fn, **kwargs):
            has_tensor = any(isinstance(a, NDArray) for a in args) or any(
                isinstance(v, NDArray) for v in kwargs.values()
            )
            return (_s if has_tensor else _r)(*args, **kwargs)

        dispatcher.__name__ = dist
        setattr(sub["random"], dist, dispatcher)
    if hasattr(sub["random"], "multinomial") is False and _reg.exists("_sample_multinomial"):
        setattr(sub["random"], "multinomial", maker(_reg.get("_sample_multinomial")))
    setattr(sub["random"], "randint", getattr(sub["random"], "randint", None) or maker(_reg.get("_random_randint")))
    setattr(sub["random"], "shuffle", maker(_reg.get("shuffle")))
    return mod


def _route(name):
    if name.startswith("_contrib_"):
        return "contrib", name[len("_contrib_"):]
    if name.startswith("_linalg_"):
        return "linalg", name[len("_linalg_"):]
    if name.startswith("_random_"):
        return "random", name[len("_random_"):]
    if name.startswith("_sample_"):
        return "random", name[len("_sample_"):]
    if name.startswith("_sparse_"):
        return "sparse", name[len("_sparse_"):]
    if name.startswith("_image_"):
        return "image", name[len("_image_"):]
    return None, name
