"""NDArray serialization.

Reference counterpart: ``NDArray::Save/Load`` (src/ndarray/ndarray.cc binary
format with magic + per-array Context/TShape/dtype blobs) and
``python/mxnet/ndarray/utils.py:185-233``. We keep the same *surface*
(``mx.nd.save``/``mx.nd.load`` of a list or str→NDArray dict, one file) on
an .npz container — portable, fast, and framework-neutral.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, array

_LIST_PREFIX = "__mx_list_%d"


def save(fname, data):
    """Save a list of NDArrays or a dict of str->NDArray to file."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        payload = {}
        for k, v in data.items():
            if not isinstance(v, NDArray):
                raise MXNetError("save: values must be NDArrays")
            payload[k] = v.asnumpy()
    elif isinstance(data, (list, tuple)):
        payload = {(_LIST_PREFIX % i): v.asnumpy() for i, v in enumerate(data)}
    else:
        raise MXNetError("save: data must be NDArray, list, or dict")
    with open(fname, "wb") as f:
        _np.savez(f, **payload)


def load(fname):
    """Load NDArrays saved by :func:`save`. Returns list or dict."""
    with _np.load(fname, allow_pickle=False) as npz:
        keys = list(npz.keys())
        if keys and all(k.startswith("__mx_list_") for k in keys):
            n = len(keys)
            return [array(npz[_LIST_PREFIX % i]) for i in range(n)]
        return {k: array(npz[k]) for k in keys}
