"""Sparse NDArrays: row_sparse and csr storage types.

Reference counterpart: ``python/mxnet/ndarray/sparse.py`` +
``src/operator/tensor/cast_storage*`` (SURVEY §2.5 sparse ops). TPU-native
design: XLA has no sparse tensors, so sparse stypes are *structured dense
pairs* — (indices, values) — with dense fallbacks (the reference's own
``kFComputeFallback`` dispatch, op_attr_types.h:107-117, made the same
move). This covers the kvstore row-sparse path and sparse optimizer tests.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, array, invoke, zeros as nd_zeros


class BaseSparseNDArray(NDArray):
    __slots__ = ("_aux", "_full_shape")

    @property
    def stype(self):
        return self._stype


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values) pair: values[i] is row indices[i] of the dense view."""

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(None, ctx=ctx)
        self._aux = {"values": data, "indices": indices}
        self._full_shape = tuple(shape)
        self._stype = "row_sparse"

    @property
    def shape(self):
        return self._full_shape

    @property
    def dtype(self):
        return self._aux["values"].dtype

    @property
    def data(self):
        return self._aux["values"]

    @property
    def indices(self):
        return self._aux["indices"]

    def _data(self):
        return self.tostype("default")._data()

    def _rebind_sparse(self, other):
        self._aux = other._aux
        self._full_shape = other._full_shape

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype != "default":
            raise MXNetError("cannot convert row_sparse to %r" % stype)
        import jax.numpy as jnp

        vals = self._aux["values"]._jax
        idx = self._aux["indices"]._jax.astype(jnp.int32)
        dense = jnp.zeros(self._full_shape, dtype=vals.dtype)
        dense = dense.at[idx].set(vals)
        return NDArray(dense, ctx=self._ctx)

    def asnumpy(self):
        return np.asarray(self.tostype("default")._data())

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other._rebind_sparse(
                RowSparseNDArray(self.data.copy(), self.indices.copy(), self._full_shape, ctx=other._ctx)
            )
            return other
        return self.tostype("default").copyto(other)

    def copy(self):
        return RowSparseNDArray(self.data.copy(), self.indices.copy(), self._full_shape, ctx=self._ctx)

    def __repr__(self):
        return "\n<RowSparseNDArray %s @%s>" % ("x".join(map(str, self._full_shape)), self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """CSR: (data, indices, indptr)."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(None, ctx=ctx)
        self._aux = {"values": data, "indices": indices, "indptr": indptr}
        self._full_shape = tuple(shape)
        self._stype = "csr"

    @property
    def shape(self):
        return self._full_shape

    @property
    def dtype(self):
        return self._aux["values"].dtype

    @property
    def data(self):
        return self._aux["values"]

    @property
    def indices(self):
        return self._aux["indices"]

    @property
    def indptr(self):
        return self._aux["indptr"]

    def _data(self):
        return self.tostype("default")._data()

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype != "default":
            raise MXNetError("cannot convert csr to %r" % stype)
        import jax.numpy as jnp

        vals = np.asarray(self._aux["values"]._jax)
        idx = np.asarray(self._aux["indices"]._jax).astype(np.int64)
        ptr = np.asarray(self._aux["indptr"]._jax).astype(np.int64)
        dense = np.zeros(self._full_shape, dtype=vals.dtype)
        for r in range(self._full_shape[0]):
            cols = idx[ptr[r] : ptr[r + 1]]
            dense[r, cols] = vals[ptr[r] : ptr[r + 1]]
        return array(dense, ctx=self._ctx)

    def asnumpy(self):
        return np.asarray(self.tostype("default")._data())

    def __repr__(self):
        return "\n<CSRNDArray %s @%s>" % ("x".join(map(str, self._full_shape)), self._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else array(data, ctx=ctx, dtype=dtype)
        indices = indices if isinstance(indices, NDArray) else array(indices, ctx=ctx, dtype=np.int64)
        if shape is None:
            raise MXNetError("row_sparse_array: shape required with (data, indices)")
        return RowSparseNDArray(data, indices, shape, ctx=ctx)
    dense = arg1 if isinstance(arg1, NDArray) else array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = data if isinstance(data, NDArray) else array(data, ctx=ctx, dtype=dtype)
        indices = indices if isinstance(indices, NDArray) else array(indices, ctx=ctx, dtype=np.int64)
        indptr = indptr if isinstance(indptr, NDArray) else array(indptr, ctx=ctx, dtype=np.int64)
        if shape is None:
            raise MXNetError("csr_matrix: shape required with (data, indices, indptr)")
        return CSRNDArray(data, indices, indptr, shape, ctx=ctx)
    dense = arg1 if isinstance(arg1, NDArray) else array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "csr")


def cast_storage(arr, stype):
    """Dense↔sparse conversion (ref: src/operator/tensor/cast_storage-inl.h)."""
    if stype == "default":
        return arr.tostype("default") if isinstance(arr, BaseSparseNDArray) else arr
    dense = arr.asnumpy()
    if stype == "row_sparse":
        nz_rows = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
        vals = dense[nz_rows]
        return RowSparseNDArray(
            array(vals, ctx=arr.ctx), array(nz_rows.astype(np.int64), ctx=arr.ctx),
            dense.shape, ctx=arr.ctx,
        )
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr requires 2D")
        indptr = [0]
        indices = []
        vals = []
        for r in range(dense.shape[0]):
            cols = np.nonzero(dense[r])[0]
            indices.extend(cols.tolist())
            vals.extend(dense[r, cols].tolist())
            indptr.append(len(indices))
        return CSRNDArray(
            array(np.asarray(vals, dtype=dense.dtype), ctx=arr.ctx),
            array(np.asarray(indices, dtype=np.int64), ctx=arr.ctx),
            array(np.asarray(indptr, dtype=np.int64), ctx=arr.ctx),
            dense.shape, ctx=arr.ctx,
        )
    raise MXNetError("unknown stype %r" % stype)


def zeros(stype, shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if stype == "default":
        return nd_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(
            array(np.zeros((0,) + tuple(shape[1:]), dtype=dtype or np.float32), ctx=ctx),
            array(np.zeros((0,), dtype=np.int64), ctx=ctx),
            shape, ctx=ctx,
        )
    if stype == "csr":
        return CSRNDArray(
            array(np.zeros((0,), dtype=dtype or np.float32), ctx=ctx),
            array(np.zeros((0,), dtype=np.int64), ctx=ctx),
            array(np.zeros((shape[0] + 1,), dtype=np.int64), ctx=ctx),
            shape, ctx=ctx,
        )
    raise MXNetError("unknown stype %r" % stype)
