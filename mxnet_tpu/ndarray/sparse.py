"""Sparse NDArrays: row_sparse and csr storage types.

Reference counterpart: ``python/mxnet/ndarray/sparse.py`` +
``src/operator/tensor/cast_storage*`` (SURVEY §2.5 sparse ops). TPU-native
design: XLA has no sparse tensors, so sparse stypes are *structured dense
pairs* — (indices, values) — with dense fallbacks (the reference's own
``kFComputeFallback`` dispatch, op_attr_types.h:107-117, made the same
move). This covers the kvstore row-sparse path and sparse optimizer tests.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, array, invoke, zeros as nd_zeros


class BaseSparseNDArray(NDArray):
    __slots__ = ("_aux", "_full_shape")

    @property
    def stype(self):
        return self._stype


class RowSparseNDArray(BaseSparseNDArray):
    """(indices, values) pair: values[i] is row indices[i] of the dense view."""

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(None, ctx=ctx)
        self._aux = {"values": data, "indices": indices}
        self._full_shape = tuple(shape)
        self._stype = "row_sparse"

    @property
    def shape(self):
        return self._full_shape

    @property
    def dtype(self):
        return self._aux["values"].dtype

    @property
    def data(self):
        return self._aux["values"]

    @property
    def indices(self):
        return self._aux["indices"]

    def _data(self):
        return self.tostype("default")._data()

    def _rebind_sparse(self, other):
        self._aux = other._aux
        self._full_shape = other._full_shape

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype != "default":
            raise MXNetError("cannot convert row_sparse to %r" % stype)
        import jax.numpy as jnp

        vals = self._aux["values"]._jax
        idx = self._aux["indices"]._jax.astype(jnp.int32)
        dense = jnp.zeros(self._full_shape, dtype=vals.dtype)
        # canonical invariant: indices are unique (aggregation sums
        # duplicates at creation, see add()), so set == add here — and
        # row_sparse_pull results with repeated row_ids stay correct
        dense = dense.at[idx].set(vals)
        return NDArray(dense, ctx=self._ctx)

    def asnumpy(self):
        return np.asarray(self.tostype("default")._data())

    def copyto(self, other):
        if isinstance(other, RowSparseNDArray):
            other._rebind_sparse(
                RowSparseNDArray(self.data.copy(), self.indices.copy(), self._full_shape, ctx=other._ctx)
            )
            return other
        return self.tostype("default").copyto(other)

    def copy(self):
        return RowSparseNDArray(self.data.copy(), self.indices.copy(), self._full_shape, ctx=self._ctx)

    def __repr__(self):
        return "\n<RowSparseNDArray %s @%s>" % ("x".join(map(str, self._full_shape)), self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """CSR: (data, indices, indptr)."""

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(None, ctx=ctx)
        self._aux = {"values": data, "indices": indices, "indptr": indptr}
        self._full_shape = tuple(shape)
        self._stype = "csr"

    @property
    def shape(self):
        return self._full_shape

    @property
    def dtype(self):
        return self._aux["values"].dtype

    @property
    def data(self):
        return self._aux["values"]

    @property
    def indices(self):
        return self._aux["indices"]

    @property
    def indptr(self):
        return self._aux["indptr"]

    def _data(self):
        return self.tostype("default")._data()

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype != "default":
            raise MXNetError("cannot convert csr to %r" % stype)
        import jax.numpy as jnp

        vals = np.asarray(self._aux["values"]._jax)
        idx = np.asarray(self._aux["indices"]._jax).astype(np.int64)
        ptr = np.asarray(self._aux["indptr"]._jax).astype(np.int64)
        dense = np.zeros(self._full_shape, dtype=vals.dtype)
        for r in range(self._full_shape[0]):
            cols = idx[ptr[r] : ptr[r + 1]]
            dense[r, cols] = vals[ptr[r] : ptr[r + 1]]
        return array(dense, ctx=self._ctx)

    def asnumpy(self):
        return np.asarray(self.tostype("default")._data())

    def __repr__(self):
        return "\n<CSRNDArray %s @%s>" % ("x".join(map(str, self._full_shape)), self._ctx)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = data if isinstance(data, NDArray) else array(data, ctx=ctx, dtype=dtype)
        indices = indices if isinstance(indices, NDArray) else array(indices, ctx=ctx, dtype=np.int64)
        if shape is None:
            raise MXNetError("row_sparse_array: shape required with (data, indices)")
        # user-supplied indices may repeat or be unsorted; every consumer
        # (tostype's .at[].set densify, the lazy optimizer kernels) assumes
        # the canonical unique-sorted invariant, so enforce it here —
        # duplicates are summed, matching the optimizer-kernel semantics
        vals, idx = _canonicalize(data._data(), indices._data())
        return RowSparseNDArray(NDArray(vals, ctx=ctx),
                                NDArray(idx.astype("int64"), ctx=ctx),
                                shape, ctx=ctx)
    dense = arg1 if isinstance(arg1, NDArray) else array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = data if isinstance(data, NDArray) else array(data, ctx=ctx, dtype=dtype)
        indices = indices if isinstance(indices, NDArray) else array(indices, ctx=ctx, dtype=np.int64)
        indptr = indptr if isinstance(indptr, NDArray) else array(indptr, ctx=ctx, dtype=np.int64)
        if shape is None:
            raise MXNetError("csr_matrix: shape required with (data, indices, indptr)")
        return CSRNDArray(data, indices, indptr, shape, ctx=ctx)
    dense = arg1 if isinstance(arg1, NDArray) else array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "csr")


def cast_storage(arr, stype):
    """Dense↔sparse conversion (ref: src/operator/tensor/cast_storage-inl.h)."""
    if stype == "default":
        return arr.tostype("default") if isinstance(arr, BaseSparseNDArray) else arr
    dense = arr.asnumpy()
    if stype == "row_sparse":
        nz_rows = np.where(np.any(dense.reshape(dense.shape[0], -1) != 0, axis=1))[0]
        vals = dense[nz_rows]
        return RowSparseNDArray(
            array(vals, ctx=arr.ctx), array(nz_rows.astype(np.int64), ctx=arr.ctx),
            dense.shape, ctx=arr.ctx,
        )
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr requires 2D")
        indptr = [0]
        indices = []
        vals = []
        for r in range(dense.shape[0]):
            cols = np.nonzero(dense[r])[0]
            indices.extend(cols.tolist())
            vals.extend(dense[r, cols].tolist())
            indptr.append(len(indices))
        return CSRNDArray(
            array(np.asarray(vals, dtype=dense.dtype), ctx=arr.ctx),
            array(np.asarray(indices, dtype=np.int64), ctx=arr.ctx),
            array(np.asarray(indptr, dtype=np.int64), ctx=arr.ctx),
            dense.shape, ctx=arr.ctx,
        )
    raise MXNetError("unknown stype %r" % stype)


# ---------------------------------------------------------------------------
# sparse compute kernels (ref: src/operator/tensor/dot-inl.h sparse dot,
# optimizer_op.cc sparse update variants). TPU-native shape: the CSR
# structure is lowered to a gather + segment-sum, which XLA tiles onto
# the MXU/VPU with static (nnz,) shapes — no dense materialization.
# ---------------------------------------------------------------------------
def _csr_row_ids(indptr, nnz):
    """Row id per stored element: repeat(arange(R), diff(indptr))."""
    import jax.numpy as jnp

    counts = indptr[1:] - indptr[:-1]
    return jnp.repeat(jnp.arange(counts.shape[0], dtype=jnp.int32),
                      counts.astype(jnp.int32), total_repeat_length=nnz)


def _jit(fn, **kw):
    """Deferred module-level jit (jax imported lazily, one compile cache
    per kernel instead of per call)."""
    import functools

    holder = {}

    @functools.wraps(fn)
    def call(*args):
        if "j" not in holder:
            import jax

            holder["j"] = jax.jit(fn, **kw)
        return holder["j"](*args)

    return call


def _csr_dot_impl(vals, cols, ptr, dense, n_seg, transpose):
    import jax

    row_ids = _csr_row_ids(ptr, vals.shape[0])
    if transpose:
        # out[c] = sum over stored (r, c): val * dense[r]
        contrib = vals[:, None] * dense[row_ids]
        return jax.ops.segment_sum(contrib, cols, num_segments=n_seg)
    contrib = vals[:, None] * dense[cols]                # (nnz, N)
    return jax.ops.segment_sum(contrib, row_ids, num_segments=n_seg)


_csr_dot_kernel = _jit(_csr_dot_impl, static_argnums=(4, 5))


def _clip(g, clip):
    import jax.numpy as jnp

    # clip < 0 means "no clipping"; branchless so clip can stay traced
    return jnp.where(clip > 0, jnp.clip(g, -jnp.abs(clip), jnp.abs(clip)), g)


def _rsp_sgd_impl(w, vals, idx, lr, wd, rescale, clip):
    g = _clip(vals * rescale, clip) + wd * w[idx]
    return w.at[idx].add(-lr * g)


def _rsp_sgd_mom_impl(w, mom, vals, idx, lr, wd, rescale, clip, momentum):
    g = _clip(vals * rescale, clip) + wd * w[idx]
    m_rows = momentum * mom[idx] - lr * g
    return w.at[idx].add(m_rows), mom.at[idx].set(m_rows)


def _rsp_adam_impl(w, m, v, vals, idx, lr_t, beta1, beta2, eps, wd,
                   rescale, clip):
    import jax.numpy as jnp

    g = _clip(vals * rescale, clip) + wd * w[idx]
    m_rows = beta1 * m[idx] + (1 - beta1) * g
    v_rows = beta2 * v[idx] + (1 - beta2) * g * g
    upd = lr_t * m_rows / (jnp.sqrt(v_rows) + eps)
    return w.at[idx].add(-upd), m.at[idx].set(m_rows), v.at[idx].set(v_rows)


_rsp_sgd_kernel = _jit(_rsp_sgd_impl)
_rsp_sgd_mom_kernel = _jit(_rsp_sgd_mom_impl)
_rsp_adam_kernel = _jit(_rsp_adam_impl)


def dot(lhs, rhs, transpose_a=False):
    """Sparse-aware dot (ref: dot-inl.h dot(csr, dense) forward and the
    dot(csr.T, dense) path used by sparse embeddings/linear models)."""
    import jax.numpy as jnp

    if not isinstance(lhs, CSRNDArray):
        from .ndarray import invoke

        if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs, BaseSparseNDArray):
            lhs = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
            rhs = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
        return invoke("dot", [lhs, rhs], {"transpose_a": transpose_a})

    dense = rhs._data() if isinstance(rhs, NDArray) else jnp.asarray(rhs)
    vec = dense.ndim == 1
    if vec:
        dense = dense[:, None]
    vals = lhs.data._data()
    cols = lhs.indices._data().astype(jnp.int32)
    ptr = lhs.indptr._data()
    rows, n_cols = lhs.shape
    out = _csr_dot_kernel(vals, cols, ptr, dense,
                          n_cols if transpose_a else rows, bool(transpose_a))
    if vec:
        out = out[:, 0]
    return NDArray(out, ctx=lhs.ctx)


def _canonicalize(vals, idx):
    """(values, indices) with unique sorted indices: duplicates summed.

    Index bookkeeping is host-side numpy (indices are tiny and the
    kvstore reduce path is host-mediated anyway); the value segment-sum
    runs on device with a static segment count."""
    import jax
    import jax.numpy as jnp

    idx_np = np.asarray(idx)
    uniq, inverse = np.unique(idx_np, return_inverse=True)
    if uniq.shape[0] == idx_np.shape[0]:
        order = np.argsort(idx_np)
        if (idx_np == uniq).all():
            return vals, idx
        return jnp.asarray(np.asarray(vals)[order]), jnp.asarray(uniq)
    summed = jax.ops.segment_sum(jnp.asarray(vals),
                                 jnp.asarray(inverse.astype(np.int32)),
                                 num_segments=int(uniq.shape[0]))
    return summed, jnp.asarray(uniq)


def add(lhs, rhs):
    """Sparse-preserving add of two RowSparseNDArrays: the kvstore
    gradient-aggregation primitive (ref: comm.h ReduceRowSparse).
    Overlapping rows are summed and the result is canonical (unique
    sorted indices) — the invariant every consumer relies on."""
    import jax.numpy as jnp

    if not (isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray)):
        raise MXNetError("sparse.add expects two RowSparseNDArrays")
    if lhs.shape != rhs.shape:
        raise MXNetError("shape mismatch %s vs %s" % (lhs.shape, rhs.shape))
    vals = jnp.concatenate([lhs.data._data(), rhs.data._data()], axis=0)
    idx = jnp.concatenate([lhs.indices._data(), rhs.indices._data()], axis=0)
    vals, idx = _canonicalize(vals, idx)
    return RowSparseNDArray(NDArray(vals, ctx=lhs.ctx), NDArray(idx, ctx=lhs.ctx),
                            lhs.shape, ctx=lhs.ctx)


def sgd_update_rsp(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=None, state=None, momentum=0.0):
    """Lazy row-sparse SGD(+momentum): only rows present in ``grad`` are
    touched (ref: optimizer_op.cc sparse sgd_update/sgd_mom_update —
    'lazy update' semantics, momentum decayed only on updated rows)."""
    vals, idx = _canonicalize(grad.data._data(), grad.indices._data())
    idx = idx.astype("int32")
    clip = -1.0 if clip_gradient is None else float(clip_gradient)
    if state is None:
        new_w = _rsp_sgd_kernel(weight._data(), vals, idx,
                                lr, wd, rescale_grad, clip)
        weight._rebind(new_w)
    else:
        new_w, new_mom = _rsp_sgd_mom_kernel(
            weight._data(), state._data(), vals, idx,
            lr, wd, rescale_grad, clip, momentum)
        weight._rebind(new_w)
        state._rebind(new_mom)
    return weight


def adam_update_rsp(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                    epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                    clip_gradient=None, t=1):
    """Lazy row-sparse Adam: moments and weight updated only on rows
    present in ``grad`` (ref: optimizer_op.cc adam_update FComputeEx)."""
    vals, idx = _canonicalize(grad.data._data(), grad.indices._data())
    idx = idx.astype("int32")
    clip = -1.0 if clip_gradient is None else float(clip_gradient)
    coef1 = 1.0 - beta1 ** t
    coef2 = 1.0 - beta2 ** t
    lr_t = lr * (coef2 ** 0.5) / coef1
    new_w, new_m, new_v = _rsp_adam_kernel(
        weight._data(), mean._data(), var._data(), vals, idx,
        lr_t, beta1, beta2, epsilon, wd, rescale_grad, clip)
    weight._rebind(new_w)
    mean._rebind(new_m)
    var._rebind(new_v)
    return weight


def zeros(stype, shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if stype == "default":
        return nd_zeros(shape, ctx=ctx, dtype=dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(
            array(np.zeros((0,) + tuple(shape[1:]), dtype=dtype or np.float32), ctx=ctx),
            array(np.zeros((0,), dtype=np.int64), ctx=ctx),
            shape, ctx=ctx,
        )
    if stype == "csr":
        return CSRNDArray(
            array(np.zeros((0,), dtype=dtype or np.float32), ctx=ctx),
            array(np.zeros((0,), dtype=np.int64), ctx=ctx),
            array(np.zeros((shape[0] + 1,), dtype=np.int64), ctx=ctx),
            shape, ctx=ctx,
        )
    raise MXNetError("unknown stype %r" % stype)
