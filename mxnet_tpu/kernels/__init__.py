"""Pallas TPU kernels — the tier where the reference used hand-written
CUDA (src/operator/*.cu, SURVEY §2.5 "TPU mapping"): ops XLA cannot fuse
well on its own get explicit MXU/VMEM-aware kernels here.

Every kernel ships with an ``interpret`` mode so the unit tests run on the
CPU mesh (SURVEY §4 test strategy); on TPU backends the compiled Mosaic
kernel runs.
"""
from .flash_attention import flash_attention

__all__ = ["flash_attention"]
