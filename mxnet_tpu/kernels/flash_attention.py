"""Flash attention as a Pallas TPU kernel (forward + backward).

Reference counterpart: none — attention post-dates the reference; this is
the flagship "custom CUDA kernel → Pallas" tier (SURVEY §2.5 TPU mapping)
and the compute core of the transformer family / ring attention
(parallel/ring.py uses the same online-softmax math across devices).

Design: O(S) memory — no materialized (S, S) score matrix.

- forward: grid (B*H, S_q/block_q); K/V stay VMEM-resident per (b, h);
  fori_loop over K blocks with online softmax (running max m, denominator
  l, unnormalized accumulator) in fp32; emits out and the logsumexp rows
  needed by backward. Causal masking prunes fully-future K blocks from
  the loop bound, so causal costs ~half the FLOPs.
- backward: recomputation strategy (no (S, S) residual): one kernel
  produces dQ (grid over Q blocks), a second produces dK/dV (grid over
  K blocks), both re-forming p = exp(qk - lse) blockwise on the MXU.

All matmuls use ``preferred_element_type=jnp.float32`` (MXU accumulates
fp32); inputs may be bf16. ``interpret=None`` auto-selects interpreter
mode off-TPU so the CPU test mesh exercises the same code path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _round_up(x, m):
    return -(-x // m) * m


def _need_interpret(interpret):
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _mask_scores(s, iq, jk, block_q, block_k, causal, kv_len, seq_k):
    """Apply causal and/or key-padding masks to a (block_q, block_k) score
    tile; kv_len < seq_k marks the tail keys as padding."""
    if not causal and kv_len == seq_k:
        return s
    cols = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    ok = None
    if causal:
        rows = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        ok = rows >= cols
    if kv_len != seq_k:
        valid = cols < kv_len
        ok = valid if ok is None else (ok & valid)
    return jnp.where(ok, s, _NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k, seq_k, kv_len):
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                  # (bq, d)
    n_kb = seq_k // block_k
    if causal:
        # K blocks strictly after this Q block's last row contribute nothing
        n_kb = jnp.minimum(n_kb, ((iq + 1) * block_q + block_k - 1) // block_k)

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = _mask_scores(s, iq, j, block_q, block_k, causal, kv_len, seq_k)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q, block_k, seq_k, kv_len):
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)                        # (bq, d)
    lse = lse_ref[0]                                          # (bq, 1)
    delta = delta_ref[0]
    n_kb = seq_k // block_k
    if causal:
        n_kb = jnp.minimum(n_kb, ((iq + 1) * block_q + block_k - 1) // block_k)

    def body(j, dq):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = _mask_scores(s, iq, j, block_q, block_k, causal, kv_len, seq_k)
        p = jnp.exp(s - lse)                                  # (bq, bk)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)                                 # (bq, bk)
        return dq + jax.lax.dot_general(ds, kb, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, n_kb, body, jnp.zeros(q.shape, jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, block_k,
                    seq_q, seq_k, kv_len):
    jk = pl.program_id(1)
    kb = k_ref[0].astype(jnp.float32)                          # (bk, d)
    vb = v_ref[0].astype(jnp.float32)
    n_qb = seq_q // block_q
    # causal: Q blocks strictly before this K block see none of it
    start_qb = (jk * block_k) // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = _mask_scores(s, i, jk, block_q, block_k, causal, kv_len, seq_k)
        p = jnp.exp(s - lse)                                   # (bq, bk)
        dv_new = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + jax.lax.dot_general(ds, qb, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = jax.lax.fori_loop(
        start_qb, n_qb, body,
        (jnp.zeros(kb.shape, jnp.float32), jnp.zeros(vb.shape, jnp.float32)))
    # qb in the loop already carries the softmax scale, so dk = ds^T @ qb
    # is fully scaled — no extra factor here (dq's kernel differs: there
    # the scale rides on s only, so dq needs the explicit * scale).
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------
def _fwd_call(q, k, v, scale, causal, block_q, block_k, interpret, kv_len):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk,
                          kv_len=kv_len),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            # (bh, sq, 1): Mosaic requires the last two block dims to be
            # (8k, 128k) or full-size; trailing singleton satisfies that
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _bwd_call(q, k, v, do, out, lse, scale, causal, block_q, block_k,
              interpret, kv_len):
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_k=sk,
                          kv_len=kv_len),
        grid=(bh, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, seq_q=sq,
                          seq_k=sk, kv_len=kv_len),
        grid=(bh, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, sq, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret, kv_len):
    out, _ = _fwd_call(q, k, v, scale, causal, block_q, block_k, interpret,
                       kv_len)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret, kv_len):
    out, lse = _fwd_call(q, k, v, scale, causal, block_q, block_k, interpret,
                         kv_len)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, kv_len, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _bwd_call(q, k, v, do, out, lse, scale, causal, block_q,
                           block_k, interpret, kv_len)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def _effective_one(block, seq):
    seq = max(int(seq), 1)
    if block >= seq:
        # full-size block: Mosaic accepts the whole dimension as one
        # tile, so clamp EXACTLY to the sequence — rounding up past it
        # would only pad. The decode shape (seq_q == 1) depends on
        # this: block_q must clamp to 1, not round up to a 16-row tile
        # the single query would rattle around in (ISSUE 12).
        return seq
    return _round_up(block, 16)


def effective_blocks(block_q, block_k, seq_q, seq_k):
    """The block sizes a (block_q, block_k) request actually runs with:
    rounded up to the 16-row Mosaic tile while smaller than the
    sequence, clamped to exactly the sequence length (a legal full-size
    tile) once they reach it. One definition shared with the schedule
    search (tune/search.py), so candidate dedup matches the kernel
    exactly."""
    return (_effective_one(block_q, seq_q), _effective_one(block_k, seq_k))


# hand default block size (MXU-native); the schedule table can override
# per (shape, dtype, backend) when block_q/block_k are left None
DEFAULT_BLOCK = 128


def flash_attention(q, k, v, *, causal=False, sm_scale=None, block_q=None,
                    block_k=None, interpret=None):
    """Fused attention, (B, H, S, D) layout. Differentiable (custom VJP).

    Sequence lengths are padded to the block size internally (padding keys
    are masked out). ``block_q``/``block_k`` are per-call schedule
    parameters (ISSUE 10): left None, the on-disk schedule table is
    consulted at trace time for this (shape, dtype, backend) — key
    ``flash_attention`` — falling back to the MXU-native 128; an
    explicit value pins the block (bench sweeps, the tuner's own timing
    path skips the consult). ``interpret=True`` forces interpreter mode
    off-TPU.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if block_q is None or block_k is None:
        from ..tune import schedule_for

        sched = schedule_for("flash_attention",
                             (b, h, sq, sk, d, int(bool(causal))),
                             str(q.dtype)) or {}
        if block_q is None:
            block_q = sched.get("block_q", DEFAULT_BLOCK)
        if block_k is None:
            block_k = sched.get("block_k", DEFAULT_BLOCK)
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    interp = _need_interpret(interpret)
    # Mosaic tiles refs as (8k, 128k) for fp32 / (16k, 128k) for bf16:
    # clamp to the sequence length but keep blocks tile-aligned (seq is
    # padded up to the block below, padded keys masked via kv_len).
    block_q, block_k = effective_blocks(block_q, block_k, sq, sk)

    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    pad_d = (-d) % 128          # lane dim: zero lanes add 0 to q·k and out
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        # padded key columns are masked to -inf inside the kernels
        # (kv_len carries the true length), so zero-padding is safe
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0)))
    if pad_d:
        qf = jnp.pad(qf, ((0, 0), (0, 0), (0, pad_d)))
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad_d)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad_d)))
    out = _flash(qf, kf, vf, scale, causal, block_q, block_k, interp, sk)
    if pad_q or pad_d:
        out = out[:, :sq, :d]
    return out.reshape(b, h, sq, d)
