"""Fused ResNet bottleneck block as Pallas TPU kernels (fwd + bwd).

Reference counterpart: the conv/BN/ReLU chains built by
``example/image-classification/symbols/resnet.py`` residual_unit — on the
reference stack each op is a separate cuDNN/CUDA kernel and the
activations round-trip device memory between them. Profiling
(PROFILE.md) shows the TPU port is HBM-bandwidth-bound the same way:
XLA materializes every BN input/output, so a ResNet-50 train step moves
~78 GB/step where ~48 GB is the structural minimum.

This module removes the extra passes with a small library of Pallas
convolution kernels in NHWC whose contract is:

- **prologue**: BatchNorm-apply + ReLU folded into the conv's *input
  read* — the normalized activation lives only in VMEM, never in HBM.
- **epilogue**: per-channel sum / sum-of-squares of the conv's *output
  write* — the next BatchNorm's statistics cost no extra pass.
- backward mirrors it: the BN/ReLU backward elementwise math rides the
  wgrad/dgrad kernels' operand reads (``bnbwd`` prologue), and dgrad
  accumulates the (dbeta, dgamma) reductions as it writes.

Every intermediate activation therefore crosses HBM exactly once, raw
(the conv output), which is the minimum any schedule with true training
BN semantics can do.

MXU blocking (round 6): the round-4/5 kernels tiled the grid
``(image, row-tile)`` so every MXU call saw a ``(th*W_out, Ci)`` row
block — at ResNet-50 shapes that is 196-784 rows against Ci,Co as
small as 64, and the on-chip measurement (PROFILE.md round 5) showed
the resulting MXU underutilization costs 2.5x more than the HBM
traffic the fusion saves. The grid is now
``(channel-block, batch-block, row-tile)`` with **the batch folded
into the matmul row dimension**: each kernel instance holds ``nb``
images' row tiles and issues matmuls of shape
``(nb*th*W_out, Ci) @ (Ci, co_block)``, with ``nb`` chosen per shape
(``_batch_fold``) so every MXU call meets the
``MXU_WORK_FLOOR = 256*256*256`` multiply-accumulate floor, and output
channels blocked to 256 lanes (``_chan_block``). Grid dimensions carry
``dimension_semantics`` — channel blocks are ``parallel``; the
batch/row dims that accumulate into a revisited output (BN stats, dw)
are ``arbitrary``. ``set_row_tile`` / ``MXNET_TPU_FUSED_ROW_TILE``
expose the row-tile size as a knob; ``mxu_plan`` reports the matmul
tile a given conv shape gets, so tests and benchmarks can assert the
work floor at real shapes.

Layout: NHWC with channels on the TPU lane dimension; weights HWIO.
1x1 convs are per-pixel matmuls; 3x3 convs are 9 shifted matmuls over a
spatially tiled block with 1-row halos (halo rows enter as extra
1-row BlockSpec operands, so no manual DMA is needed). Stride-2
backward uses zero-stuffed input tiles (transposed conv), built with
interleave/concat only — no pad/scatter primitives, so the kernels
lower on Mosaic and run identically under ``interpret``.

``interpret=None`` auto-selects interpreter mode off-TPU so the CPU
test mesh runs the same code path (same convention as
flash_attention.py).
"""
from __future__ import annotations

import contextlib
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as _np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from .. import config as _config
from ..util import shard_map as _shard_map

# One MXU call must see at least this many multiply-accumulates
# (M*K*N >= 256^3): below it the systolic array spends its cycles on
# fill/drain instead of work — the measured round-5 failure mode.
MXU_WORK_FLOOR = 256 * 256 * 256

# Per-array per-block VMEM element budget for the batch-fold chooser
# (~2 MB bf16 / 4 MB f32 per array; Pallas double-buffers inputs, so
# the practical ceiling across all of a kernel's blocks stays well
# under the 16 MB scoped-vmem limit).
_VMEM_BLOCK_ELEMS = 1 << 20

# Row-tile knob: rows of conv output per grid tile (per image). None ->
# MXNET_TPU_FUSED_ROW_TILE env var -> 16. Settable at runtime with
# set_row_tile() for sweeps (tools/bench_kernel.py --row-tile).
ROW_TILE = None

# parsed MXNET_TPU_FUSED_ROW_TILE, keyed by the raw env string so a
# changed env var between calls still takes effect but the strict
# parse runs once per value, not per kernel invocation
_ROW_TILE_ENV_CACHE = None


def set_row_tile(v):
    """Set the module-wide row-tile knob (None restores the default)."""
    global ROW_TILE
    ROW_TILE = v


def _row_tile_default():
    global _ROW_TILE_ENV_CACHE
    if ROW_TILE is not None:
        return max(1, int(ROW_TILE))
    raw = _config.get("MXNET_TPU_FUSED_ROW_TILE")
    if _ROW_TILE_ENV_CACHE is not None and _ROW_TILE_ENV_CACHE[0] == raw:
        return _ROW_TILE_ENV_CACHE[1]
    if raw in (None, ""):
        val = 16
    else:
        # strict parse: a malformed knob is a job misconfiguration —
        # fail loudly with the knob name, never train on a silently
        # substituted default (the pre-ISSUE-10 read swallowed it)
        val = _config.get_positive_int("MXNET_TPU_FUSED_ROW_TILE")
    _ROW_TILE_ENV_CACHE = (raw, val)
    return val


def _need_interpret(interpret):
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _tile_rows(h_out, limit=None):
    """Output rows per grid tile: the largest divisor of H_out <= the
    row-tile knob (default 16)."""
    if limit is None:
        limit = _row_tile_default()
    for cand in range(min(limit, h_out), 0, -1):
        if h_out % cand == 0:
            return cand
    return 1


def _chan_block(c):
    """Output-channel block: 256 lanes when c divides into 256-blocks
    (ResNet channels are powers of two), else the whole axis."""
    if c > 256 and c % 256 == 0:
        return 256
    return c


def _batch_fold(n, per_img_rows, kdim, ndim, per_img_elems):
    """Images folded into the matmul row dimension: the smallest divisor
    ``nb`` of ``n`` whose ``(nb*per_img_rows, kdim) @ (kdim, ndim)``
    matmul meets MXU_WORK_FLOOR, capped so the dominant per-block array
    (``nb*per_img_elems`` elements) stays inside the VMEM budget. When
    even the largest admissible fold misses the floor (tiny test
    shapes), the largest admissible fold is used."""
    best = 1
    for nb in range(1, n + 1):
        if n % nb:
            continue
        if nb > 1 and nb * per_img_elems > _VMEM_BLOCK_ELEMS:
            break
        best = nb
        if nb * per_img_rows * kdim * ndim >= MXU_WORK_FLOOR:
            break
    return best


def _dim_semantics(accumulates):
    """compiler_params for the (channel-block, batch-block, row-tile)
    grid: channel blocks touch disjoint output blocks (parallel); the
    batch/row dims are sequential (arbitrary) whenever they accumulate
    into a revisited output ref (BN stats, dw)."""
    sem = ("parallel",) + (("arbitrary",) * 2 if accumulates
                           else ("parallel",) * 2)
    return pltpu.TPUCompilerParams(dimension_semantics=sem)


def _pad_w(v, left=1, right=1):
    """Zero-pad the W (second-to-last of 4) axis via concat (Mosaic-safe)."""
    nb, rows, _, c = v.shape
    z = jnp.zeros((nb, rows, 1, c), v.dtype)
    parts = [z] * left + [v] + [z] * right
    return jnp.concatenate(parts, axis=2)


def _interleave_zeros(v, axis, offset):
    """Double ``axis`` by interleaving zeros; v lands at offset::2."""
    z = jnp.zeros_like(v)
    pair = (v, z) if offset == 0 else (z, v)
    stacked = jnp.stack(pair, axis=axis + 1)
    shape = list(v.shape)
    shape[axis] *= 2
    return stacked.reshape(shape)


def _subsample2(a, off_r, nr, off_c, nc):
    """``a[:, off_r:off_r+2*nr:2, off_c:off_c+2*nc:2, :]`` for a 4D
    (batch-fold, rows, cols, ch) value, Mosaic-safe: jnp multi-axis
    strided indexing lowers to a >2D gather, which the TPU lowering
    rejects ("Only 2D gather is supported"). Instead take a contiguous
    even-length slice and split ONE spatial axis at a time into
    (count, 2), selecting the parity lane with a static unit index (one
    axis per reshape keeps every intermediate <= 5D). When ``off +
    2*count`` overruns by one (the dy=2 halo case), shift the window
    one left — the selected elements are the same, at parity 1."""
    nb, rows, cols, ch = a.shape
    sr = off_r if off_r + 2 * nr <= rows else off_r - 1
    sc = off_c if off_c + 2 * nc <= cols else off_c - 1
    a = a[:, sr:sr + 2 * nr, sc:sc + 2 * nc, :]
    a = a.reshape(nb, nr, 2, 2 * nc, ch)[:, :, off_r - sr]
    return a.reshape(nb, nr, nc, 2, ch)[:, :, :, off_c - sc]


def _apply_prologue(x, pro, compute_dtype):
    """BN-apply (+ ReLU) on a VMEM-resident value, f32 math."""
    if pro is None:
        return x.astype(compute_dtype)
    scale, bias, relu = pro
    h = x.astype(jnp.float32) * scale + bias
    if relu:
        h = jnp.maximum(h, 0.0)
    return h.astype(compute_dtype)


def _bnbwd_value(e, y_raw, consts):
    """Reconstruct dL/dy from the relu-masked partial ``e`` in VMEM.

    With xhat = (y - mu) * inv_sigma and forward out = gamma*xhat + beta,
    the relu-masked upstream grad e gives
    dL/dy = (gamma * inv_sigma) * (e - m0 - xhat * m1),
    where m0 = mean(e), m1 = mean(e * xhat) over the batch.
    ``consts`` = (k = gamma*inv_sigma, mu, inv_sigma, m0, m1), (1,1,C) f32.
    """
    k, mu, inv_sigma, m0, m1 = consts
    ef = e.astype(jnp.float32)
    xhat = (y_raw.astype(jnp.float32) - mu) * inv_sigma
    return k * (ef - m0 - xhat * m1)


def _nine_shift_matmul(hp, w_ref, th_out, w_out, stride):
    """Core of the 3x3 conv: 9 shifted (nb*th_out*w_out, Ci) @ (Ci, Co)
    matmuls on a W-padded block ``hp`` of shape
    (nb, rows_in, W_out*stride + 2, Ci) — the batch fold rides the row
    dimension, so each MXU call sees the full nb-image tile."""
    nb = hp.shape[0]
    ci = hp.shape[-1]
    co = w_ref.shape[-1]
    acc = jnp.zeros((nb * th_out * w_out, co), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            if stride == 1:
                xs = hp[:, dy:dy + th_out, dx:dx + w_out, :]
            else:
                xs = _subsample2(hp, dy, th_out, dx, w_out)
            acc += jnp.dot(xs.reshape(nb * th_out * w_out, ci), w_ref[dy, dx],
                           preferred_element_type=jnp.float32)
    return acc


def _accumulate_out(ref, value, is_first):
    """Accumulate into an output ref revisited across the whole grid."""
    _accumulate_slot(ref, ..., value, is_first)


def _accumulate_slot(ref, idx, value, is_first):
    """Accumulate into one static (dy, dx) slot of a revisited (k, k, Ci,
    Co) output ref. Writing tap-by-tap keeps peak VMEM at one (Ci, Co)
    partial instead of materializing all k*k taps before the store — the
    stacked form overflowed the 16 MB scoped-vmem limit at 3x3x512x512
    (9.4 MB accumulator + 9.4 MB stacked taps)."""
    @pl.when(is_first)
    def _():
        ref[idx] = value

    @pl.when(jnp.logical_not(is_first))
    def _():
        ref[idx] = ref[idx] + value


def _vec_spec(cdim, blocked=False):
    """(1, 1, C) per-channel constant. ``blocked=True``: C is the
    channel-blocked axis — follow grid dim 0."""
    if blocked:
        return pl.BlockSpec((1, 1, cdim), lambda c_, b_, i_: (0, 0, c_))
    return pl.BlockSpec((1, 1, cdim), lambda c_, b_, i_: (0, 0, 0))


def _mask_halo_rows(hv, i, top_bad, bottom_bad):
    """Zero out-of-image halo rows (padding applies to the normalized
    activation, matching the unfused graph's zero-pad of act). Row axis
    is 1 of the (nb, rows, W, C) block; every folded image shares the
    same tile position, so one row mask covers all nb."""
    rows = hv.shape[1]
    rid = jax.lax.broadcasted_iota(jnp.int32, (1, rows, 1, 1), 1)
    bad = None
    if top_bad:
        bad = jnp.logical_and(i == 0, rid == 0)
    if bottom_bad:
        b = jnp.logical_and(i == pl.num_programs(2) - 1, rid == rows - 1)
        bad = b if bad is None else jnp.logical_or(bad, b)
    if bad is None:
        return hv
    return jnp.where(bad, jnp.zeros_like(hv), hv)


# ---------------------------------------------------------------------------
# blocking plans: one source of truth for kernels, tests, and benchmarks
# ---------------------------------------------------------------------------
def _per_img_conv(th, wo, ci, bco, k, stride):
    """Dominant per-image per-block element count of the conv_fwd/wgrad
    geometry (the VMEM budget term of the batch-fold chooser)."""
    rows_in = stride * th
    wd = wo * stride
    return max((rows_in + (2 if k == 3 else 0)) * wd * ci, th * wo * bco)


def _per_img_dgrad(th_in, th_g, wd, bci, co, k, stride):
    """Dominant per-image per-block element count of conv_dgrad."""
    wo = wd // stride
    return max(th_in * wd * bci, (th_g + 2) * wo * co)


def _plan_conv(n, ho, wo, ci, co, k, stride, row_tile=None,
               chan_block=None, batch_fold=None):
    """Grid plan shared by conv_fwd and conv_wgrad (same geometry):
    (th, ht, rows_in, nb, nbb, bco, cb). ``chan_block``/``batch_fold``
    force a searched schedule's blocks (callers validate divisibility —
    schedule_legal / _schedule_knobs)."""
    # NOT equivalent to _tile_rows(ho, row_tile): tests monkeypatch
    # _tile_rows with a single-arg lambda (test_fused_resnet.py), so
    # the default path must call it with one argument
    th = _tile_rows(ho) if row_tile is None else _tile_rows(ho, row_tile)
    ht = ho // th
    rows_in = stride * th
    bco = chan_block if chan_block else _chan_block(co)
    cb = co // bco
    per_img = _per_img_conv(th, wo, ci, bco, k, stride)
    nb = (batch_fold if batch_fold
          else _batch_fold(n, th * wo, ci, bco, per_img))
    return th, ht, rows_in, nb, n // nb, bco, cb


def _plan_dgrad(n, h, wd, ci, co, k, stride, row_tile=None,
                chan_block=None, batch_fold=None):
    """Grid plan for conv_dgrad: (th_in, ht, th_g, nb, nbb, bci, cib)."""
    # single-arg default call: see the monkeypatch note in _plan_conv
    th_in = _tile_rows(h) if row_tile is None else _tile_rows(h, row_tile)
    if stride == 2 and th_in % 2:
        th_in = 2 if h % 2 == 0 else 1
    ht = h // th_in
    th_g = th_in // stride
    bci = chan_block if chan_block else _chan_block(ci)
    cib = ci // bci
    wo = wd // stride
    rows_img = th_g * wo if k == 1 else th_in * wd
    per_img = _per_img_dgrad(th_in, th_g, wd, bci, co, k, stride)
    nb = (batch_fold if batch_fold
          else _batch_fold(n, rows_img, co, bci, per_img))
    return th_in, ht, th_g, nb, n // nb, bci, cib


def _sched_parts(schedule, row_tile=None):
    s = schedule or {}
    return (s.get("row_tile", row_tile), s.get("chan_block"),
            s.get("batch_fold"))


def mxu_plan(kind, x_shape, w_shape, stride=1, row_tile=None,
             schedule=None):
    """The matmul tile each MXU call sees for a kernel at these shapes.

    kind: 'fwd' | 'wgrad' | 'dgrad'; x_shape: the conv *input* NHWC
    shape; w_shape: (k, k, Ci, Co) HWIO; ``schedule``: an optional
    searched {row_tile, chan_block, batch_fold} to plan instead of the
    hand defaults (the tuner's legality/work oracle). Returns a dict
    with the grid, the per-call matmul dims (m, k, n) and their product
    ``work`` — tests assert ``work >= MXU_WORK_FLOOR`` at real
    ResNet-50 block shapes (the tentpole contract of the round-6
    rewrite)."""
    rt, cbk, bfd = _sched_parts(schedule, row_tile)
    n, h, wd, ci = x_shape
    kk = int(w_shape[0])
    co = int(w_shape[-1])
    if kind in ("fwd", "wgrad"):
        ho, wo = h // stride, wd // stride
        th, ht, rows_in, nb, nbb, bco, cb = _plan_conv(
            n, ho, wo, ci, co, kk, stride, rt, cbk, bfd)
        rows = nb * th * wo
        m, kd, nd = ((rows, ci, bco) if kind == "fwd"
                     else (ci, rows, bco))
        return dict(kind=kind, grid=(cb, nbb, ht), nb=nb, th=th, bco=bco,
                    m=m, k=kd, n=nd, work=m * kd * nd,
                    calls=kk * kk, floor=MXU_WORK_FLOOR)
    if kind == "dgrad":
        th_in, ht, th_g, nb, nbb, bci, cib = _plan_dgrad(
            n, h, wd, ci, co, kk, stride, rt, cbk, bfd)
        rows = nb * (th_g * (wd // stride) if kk == 1 else th_in * wd)
        return dict(kind=kind, grid=(cib, nbb, ht), nb=nb, th=th_in,
                    bco=bci, m=rows, k=co, n=bci, work=rows * co * bci,
                    calls=kk * kk, floor=MXU_WORK_FLOOR)
    raise ValueError("mxu_plan kind must be fwd|wgrad|dgrad, got %r"
                     % (kind,))


def schedule_legal(kind, x_shape, w_shape, stride, schedule):
    """(ok, reason) for a candidate schedule at these shapes — the
    tuner's pre-timing pruning predicate. Rejects tile > dim,
    non-dividing tiles/blocks (they would silently clamp into another
    candidate's plan), odd row tiles under the stride-2 dgrad
    zero-stuffing, and batch folds that overrun the per-block VMEM
    budget."""
    n, h, wd, ci = x_shape
    k = int(w_shape[0])
    co = int(w_shape[-1])
    rt, cbk, bfd = _sched_parts(schedule)
    rows = h if kind == "dgrad" else h // stride
    if rt is not None:
        if rt > rows:
            return False, "row_tile %d > %d output rows" % (rt, rows)
        if rows % rt:
            return False, "row_tile %d does not divide %d rows" % (rt, rows)
        if kind == "dgrad" and stride == 2 and rt % 2:
            return False, "odd row_tile %d with stride-2 dgrad" % rt
    cdim = ci if kind == "dgrad" else co
    if cbk is not None and (cbk > cdim or cdim % cbk):
        return False, "chan_block %d does not tile %d channels" % (cbk, cdim)
    if bfd is not None:
        if bfd > n or n % bfd:
            return False, "batch_fold %d does not tile batch %d" % (bfd, n)
        if bfd > 1:
            th = _tile_rows(rows, rt) if rt is not None else _tile_rows(rows)
            bc = cbk if cbk else _chan_block(cdim)
            if kind == "dgrad":
                per_img = _per_img_dgrad(th, th // stride, wd, bc, co, k,
                                         stride)
            else:
                per_img = _per_img_conv(th, wd // stride, ci, bc, k, stride)
            if bfd * per_img > _VMEM_BLOCK_ELEMS:
                return False, ("batch_fold %d x %d elems overruns the VMEM "
                               "block budget" % (bfd, per_img))
    return True, ""


def _schedule_knobs(kind, key_shape, dtype, schedule, row_tile):
    """Resolve one conv kernel call's (row_tile, chan_block,
    batch_fold). Precedence: explicit ``schedule``/``row_tile`` args
    (the tuner's own timing path and bench sweeps) > the module
    ``ROW_TILE`` global (set_row_tile) > the on-disk schedule table
    (trace-time consult, ISSUE 10) > the hand defaults. A table entry
    that is illegal for the shape (hand-edited/corrupt) counts a
    fallback and yields the defaults — it must never crash a job."""
    if schedule is not None:
        return _sched_parts(schedule, row_tile)
    if row_tile is not None or ROW_TILE is not None \
            or _config.get("MXNET_TPU_FUSED_ROW_TILE") not in (None, ""):
        # every manual override — explicit arg, set_row_tile, or the
        # env knob — pins the hand plan and beats the table (README
        # contract: the knob is the debugging escape hatch)
        return row_tile, None, None
    from ..tune import make_key, schedule_for

    s = schedule_for("fused_" + kind, key_shape, str(dtype))
    if not s:
        return None, None, None
    n, h, wd, ci, co, k, stride = key_shape
    ok, _reason = schedule_legal(kind, (n, h, wd, ci), (k, k, ci, co),
                                 stride, s)
    if not ok:
        import jax

        from .. import profiler

        # overwrite the lookup's per-kernel "table" claim: the stored
        # schedule was REJECTED and the hand defaults ran
        profiler.tuning_record(
            fallbacks=1,
            kernel=make_key("fused_" + kind, key_shape, str(dtype),
                            jax.default_backend()),
            schedule=None, source="fallback_illegal")
        return None, None, None
    return _sched_parts(s)


# ---------------------------------------------------------------------------
# forward conv (k in {1,3}, stride in {1,2}), BN-apply prologue, stats
# epilogue
# ---------------------------------------------------------------------------
def conv_fwd(x, w, *, stride=1, prologue=None, emit_stats=False,
             interpret=None, row_tile=None, schedule=None):
    """NHWC conv: y = conv(act(bn(x)), w).

    x: (N, H, W, Ci); w: (k, k, Ci, Co) with k in {1, 3} (pad = k // 2);
    prologue: None or (scale, bias, relu) with (Ci,) f32 vectors —
    per-channel folded BN apply; emit_stats: additionally return a
    (2, Co) f32 [sum, sum_sq] over the *stored* (dtype-cast) output.
    Returns (y, stats|None). ``schedule``: explicit searched
    {row_tile, chan_block, batch_fold} (the tuner's timing path); when
    absent and no row-tile override is active, the on-disk schedule
    table is consulted at trace time (tune.schedule_for) with the hand
    defaults as fallback.

    Grid: (Co-block, batch-block, row-tile); each kernel instance holds
    ``nb`` images and its matmuls are (nb*th*Wo, Ci) @ (Ci, bco).
    """
    n, h, wd, ci = x.shape
    k = int(w.shape[0])
    co = int(w.shape[-1])
    if stride == 2 and (h % 2 or wd % 2):
        # the unfused conv emits ceil((h-1)/2)+1 rows on odd inputs; the
        # tiled kernels only implement the even case — fail loudly
        # rather than silently computing a different network
        raise ValueError(
            "fused conv: stride-2 requires even spatial dims, got "
            "(%d, %d)" % (h, wd))
    ho, wo = h // stride, wd // stride
    rt, cbk, bfd = _schedule_knobs("fwd", (n, h, wd, ci, co, k, stride),
                                   x.dtype, schedule, row_tile)
    th, ht, rows_in, nb, nbb, bco, cb = _plan_conv(
        n, ho, wo, ci, co, k, stride, rt, cbk, bfd)
    dtype = x.dtype
    has_pro = prologue is not None
    relu = bool(prologue[2]) if has_pro else False

    operands, in_specs = [], []
    if has_pro:
        scale, bias, _ = prologue
        operands += [scale.reshape(1, 1, ci).astype(jnp.float32),
                     bias.reshape(1, 1, ci).astype(jnp.float32)]
        in_specs += [_vec_spec(ci), _vec_spec(ci)]
    nvec = len(operands)

    in_specs.append(pl.BlockSpec((nb, rows_in, wd, ci),
                                 lambda c_, b_, i_: (b_, i_, 0, 0)))
    operands.append(x)
    nx = 1
    if k == 3:
        in_specs.append(pl.BlockSpec(
            (nb, 1, wd, ci),
            lambda c_, b_, i_: (b_, jnp.maximum(rows_in * i_ - 1, 0), 0, 0)))
        operands.append(x)
        nx += 1
        if stride == 1:
            in_specs.append(pl.BlockSpec(
                (nb, 1, wd, ci),
                lambda c_, b_, i_: (b_, jnp.minimum(th * i_ + th, h - 1),
                                    0, 0)))
            operands.append(x)
            nx += 1
    in_specs.append(pl.BlockSpec((k, k, ci, bco),
                                 lambda c_, b_, i_: (0, 0, 0, c_)))
    operands.append(w)

    out_shapes = [jax.ShapeDtypeStruct((n, ho, wo, co), dtype)]
    out_specs = [pl.BlockSpec((nb, th, wo, bco),
                              lambda c_, b_, i_: (b_, i_, 0, c_))]
    if emit_stats:
        out_shapes.append(jax.ShapeDtypeStruct((2, co), jnp.float32))
        out_specs.append(pl.BlockSpec((2, bco), lambda c_, b_, i_: (0, c_)))

    def kernel(*refs):
        vec_refs = refs[:nvec]
        x_refs = refs[nvec:nvec + nx]
        w_ref = refs[nvec + nx]
        y_ref = refs[nvec + nx + 1]
        stats_ref = refs[nvec + nx + 2] if emit_stats else None

        i = pl.program_id(2)
        is_first = jnp.logical_and(pl.program_id(1) == 0, i == 0)
        pro = (vec_refs[0][0], vec_refs[1][0], relu) if has_pro else None

        xc = x_refs[0][...]                          # (nb, rows_in, W, Ci)
        if k == 3:
            parts = [x_refs[1][...], xc]
            if stride == 1:
                parts.append(x_refs[2][...])
            xin = jnp.concatenate(parts, axis=1)
            hv = _apply_prologue(xin, pro, dtype)
            hv = _mask_halo_rows(hv, i, top_bad=True, bottom_bad=(stride == 1))
            hp = _pad_w(hv)
            acc = _nine_shift_matmul(hp, w_ref, th, wo, stride)
        else:
            hv = _apply_prologue(xc, pro, dtype)
            if stride == 2:
                hv = _subsample2(hv, 0, th, 0, wo)
            acc = jnp.dot(hv.reshape(nb * th * wo, ci), w_ref[0, 0],
                          preferred_element_type=jnp.float32)

        y = acc.astype(dtype)
        y_ref[...] = y.reshape(nb, th, wo, bco)
        if emit_stats:
            yf = y.astype(jnp.float32)
            s = jnp.stack([jnp.sum(yf, axis=0), jnp.sum(yf * yf, axis=0)])
            _accumulate_out(stats_ref, s, is_first)

    out = pl.pallas_call(
        kernel,
        grid=(cb, nbb, ht),
        in_specs=in_specs,
        out_specs=out_specs if emit_stats else out_specs[0],
        out_shape=out_shapes if emit_stats else out_shapes[0],
        compiler_params=_dim_semantics(accumulates=emit_stats),
        interpret=_need_interpret(interpret),
    )(*operands)
    return (out[0], out[1]) if emit_stats else (out, None)


# ---------------------------------------------------------------------------
# weight gradient: dw = sum_pixels act(bn(x))^T (.) g, with the BN backward
# reconstruction of g riding the g-side read
# ---------------------------------------------------------------------------
def conv_wgrad(x, g_parts, w_shape, *, stride=1, x_prologue=None,
               g_bnbwd=None, interpret=None, row_tile=None, schedule=None):
    """dw for conv_fwd, accumulated f32 across the whole grid.

    x: (N, H, W, Ci) raw input; g_parts: the complete output gradient
    (N, Ho, Wo, Co) when ``g_bnbwd`` is None, else ``(e, y_raw)`` from
    which dL/dy is reconstructed per tile (see _bnbwd_value);
    w_shape: (k, k, Ci, Co); x_prologue: (scale, bias, relu) BN-apply
    consts for the x side; ``schedule``: see conv_fwd (table key
    ``fused_wgrad``).

    Grid: (Co-block, batch-block, row-tile) — Co-block outermost so the
    revisited f32 dw accumulator stays VMEM-resident across the whole
    (batch, row) sweep; the batch fold rides the matmul *contraction*
    dim: each call is (Ci, nb*th*Wo) @ (nb*th*Wo, bco).
    """
    n, h, wd, ci = x.shape
    k = int(w_shape[0])
    co = int(w_shape[-1])
    ho, wo = h // stride, wd // stride
    rt, cbk, bfd = _schedule_knobs("wgrad", (n, h, wd, ci, co, k, stride),
                                   x.dtype, schedule, row_tile)
    th, ht, rows_in, nb, nbb, bco, cb = _plan_conv(
        n, ho, wo, ci, co, k, stride, rt, cbk, bfd)
    dtype = x.dtype
    has_xpro = x_prologue is not None
    x_relu = bool(x_prologue[2]) if has_xpro else False

    operands, in_specs = [], []
    if has_xpro:
        operands += [x_prologue[0].reshape(1, 1, ci).astype(jnp.float32),
                     x_prologue[1].reshape(1, 1, ci).astype(jnp.float32)]
        in_specs += [_vec_spec(ci), _vec_spec(ci)]
    n_xvec = len(operands)
    if g_bnbwd is not None:
        operands += [c.reshape(1, 1, co).astype(jnp.float32) for c in g_bnbwd]
        in_specs += [_vec_spec(bco, blocked=True)] * 5
    nvec = len(operands)

    in_specs.append(pl.BlockSpec((nb, rows_in, wd, ci),
                                 lambda c_, b_, i_: (b_, i_, 0, 0)))
    operands.append(x)
    nx = 1
    if k == 3:
        in_specs.append(pl.BlockSpec(
            (nb, 1, wd, ci),
            lambda c_, b_, i_: (b_, jnp.maximum(rows_in * i_ - 1, 0), 0, 0)))
        operands.append(x)
        nx += 1
        if stride == 1:
            in_specs.append(pl.BlockSpec(
                (nb, 1, wd, ci),
                lambda c_, b_, i_: (b_, jnp.minimum(th * i_ + th, h - 1),
                                    0, 0)))
            operands.append(x)
            nx += 1
    g_spec = pl.BlockSpec((nb, th, wo, bco),
                          lambda c_, b_, i_: (b_, i_, 0, c_))
    if g_bnbwd is None:
        in_specs.append(g_spec)
        operands.append(g_parts)
        n_g = 1
    else:
        in_specs += [g_spec, g_spec]
        operands += [g_parts[0], g_parts[1]]
        n_g = 2

    def kernel(*refs):
        vec_refs = refs[:nvec]
        x_refs = refs[nvec:nvec + nx]
        g_refs = refs[nvec + nx:nvec + nx + n_g]
        dw_ref = refs[nvec + nx + n_g]

        i = pl.program_id(2)
        is_first = jnp.logical_and(pl.program_id(1) == 0, i == 0)
        pro = (vec_refs[0][0], vec_refs[1][0], x_relu) if has_xpro else None

        if g_bnbwd is None:
            g_val = g_refs[0][...].astype(jnp.float32)
        else:
            consts = tuple(vec_refs[n_xvec + j][...] for j in range(5))
            g_val = _bnbwd_value(g_refs[0][...], g_refs[1][...], consts)
        gf = g_val.reshape(nb * th * wo, bco).astype(dtype)

        xc = x_refs[0][...]
        if k == 3:
            parts = [x_refs[1][...], xc]
            if stride == 1:
                parts.append(x_refs[2][...])
            xin = jnp.concatenate(parts, axis=1)
            hv = _apply_prologue(xin, pro, dtype)
            hv = _mask_halo_rows(hv, i, top_bad=True, bottom_bad=(stride == 1))
            hp = _pad_w(hv)
            for dy in range(3):
                for dx in range(3):
                    if stride == 1:
                        xs = hp[:, dy:dy + th, dx:dx + wo, :]
                    else:
                        xs = _subsample2(hp, dy, th, dx, wo)
                    cur = jax.lax.dot_general(
                        xs.reshape(nb * th * wo, ci), gf,
                        dimension_numbers=(((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    _accumulate_slot(dw_ref, (dy, dx), cur, is_first)
        else:
            hv = _apply_prologue(xc, pro, dtype)
            if stride == 2:
                hv = _subsample2(hv, 0, th, 0, wo)
            dw = jax.lax.dot_general(
                hv.reshape(nb * th * wo, ci), gf,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).reshape(1, 1, ci, bco)
            _accumulate_out(dw_ref, dw, is_first)

    return pl.pallas_call(
        kernel,
        grid=(cb, nbb, ht),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((k, k, ci, bco),
                               lambda c_, b_, i_: (0, 0, 0, c_)),
        out_shape=jax.ShapeDtypeStruct((k, k, ci, co), jnp.float32),
        compiler_params=_dim_semantics(accumulates=True),
        interpret=_need_interpret(interpret),
    )(*operands)


# ---------------------------------------------------------------------------
# data gradient: e_out = mask(y_in) * (g (*) w^T), plus (dbeta, dgamma)
# accumulation — the BN-backward input-side partial for the next layer down
# ---------------------------------------------------------------------------
def conv_dgrad(g_parts, w, x_shape, *, stride=1, g_bnbwd=None,
               out_mask=None, extra=None, interpret=None, row_tile=None,
               schedule=None):
    """Input gradient of conv_fwd with fused epilogue.

    g_parts: complete gradient (N, Ho, Wo, Co), or ``(e, y_raw)`` with
    ``g_bnbwd`` consts; w: (k, k, Ci, Co); x_shape: (N, H, W, Ci).

    out_mask: None → returns (dx, None) with raw dL/dx. Or (y_in,
    gamma, beta, mu, inv_sigma) — the conv input's own BN: computes
    mask = (gamma*xhat + beta > 0), returns (e_out, stats) where
    e_out = mask * dL/dact and stats is (2, Ci) f32
    [sum(e_out), sum(e_out*xhat)] = (dbeta, dgamma) of that BN.

    extra: optional (g2, w2, stride2) second 1x1-conv contribution
    added to dL/dact before masking (the downsample unit's shortcut
    join at act1); g2 is a complete gradient at stride2 resolution.

    Grid: (Ci-block, batch-block, row-tile); the batch fold rides the
    matmul row dimension: each call is (nb*rows, Co) @ (Co, bci);
    ``schedule``: see conv_fwd (table key ``fused_dgrad``).
    """
    n, h, wd, ci = x_shape
    k = int(w.shape[0])
    co = int(w.shape[-1])
    ho, wo = h // stride, wd // stride
    rt, cbk, bfd = _schedule_knobs("dgrad", (n, h, wd, ci, co, k, stride),
                                   w.dtype, schedule, row_tile)
    th_in, ht, th_g, nb, nbb, bci, cib = _plan_dgrad(
        n, h, wd, ci, co, k, stride, rt, cbk, bfd)
    dtype = w.dtype

    # flipped, io-transposed kernel: dgrad = conv(g_stuffed, wflip)
    wflip = jnp.flip(jnp.flip(w, 0), 1).transpose(0, 1, 3, 2)  # (k,k,Co,Ci)

    operands, in_specs = [], []
    if g_bnbwd is not None:
        operands += [c.reshape(1, 1, co).astype(jnp.float32) for c in g_bnbwd]
        in_specs += [_vec_spec(co)] * 5
    n_gvec = len(operands)
    if out_mask is not None:
        y_in, m_gamma, m_beta, m_mu, m_inv = out_mask
        operands += [v.reshape(1, 1, ci).astype(jnp.float32)
                     for v in (m_gamma, m_beta, m_mu, m_inv)]
        in_specs += [_vec_spec(bci, blocked=True)] * 4
    nvec = len(operands)

    halo_top = k == 3 and stride == 1
    halo_bot = k == 3                       # s2 zero-stuff needs g[h0+th_g]
    n_g_blocks = 1 + int(halo_top) + int(halo_bot)
    g_ops = [g_parts] if g_bnbwd is None else [g_parts[0], g_parts[1]]
    for op in g_ops:
        in_specs.append(pl.BlockSpec((nb, th_g, wo, co),
                                     lambda c_, b_, i_: (b_, i_, 0, 0)))
        operands.append(op)
        if halo_top:
            in_specs.append(pl.BlockSpec(
                (nb, 1, wo, co),
                lambda c_, b_, i_: (b_, jnp.maximum(th_g * i_ - 1, 0), 0, 0)))
            operands.append(op)
        if halo_bot:
            in_specs.append(pl.BlockSpec(
                (nb, 1, wo, co),
                lambda c_, b_, i_: (b_, jnp.minimum(th_g * i_ + th_g, ho - 1),
                                    0, 0)))
            operands.append(op)

    in_specs.append(pl.BlockSpec((k, k, co, bci),
                                 lambda c_, b_, i_: (0, 0, 0, c_)))
    operands.append(wflip)
    if extra is not None:
        g2, w2, s2 = extra
        co2 = int(w2.shape[-1])
        w2t = w2.reshape(ci, co2).T.astype(dtype)            # (Co2, Ci)
        th_g2 = th_in // s2
        in_specs.append(pl.BlockSpec((nb, th_g2, wd // s2, co2),
                                     lambda c_, b_, i_: (b_, i_, 0, 0)))
        operands.append(g2)
        in_specs.append(pl.BlockSpec((co2, bci),
                                     lambda c_, b_, i_: (0, c_)))
        operands.append(w2t)
    if out_mask is not None:
        in_specs.append(pl.BlockSpec((nb, th_in, wd, bci),
                                     lambda c_, b_, i_: (b_, i_, 0, c_)))
        operands.append(y_in)

    out_shapes = [jax.ShapeDtypeStruct((n, h, wd, ci), dtype)]
    out_specs = [pl.BlockSpec((nb, th_in, wd, bci),
                              lambda c_, b_, i_: (b_, i_, 0, c_))]
    if out_mask is not None:
        out_shapes.append(jax.ShapeDtypeStruct((2, ci), jnp.float32))
        out_specs.append(pl.BlockSpec((2, bci), lambda c_, b_, i_: (0, c_)))

    def kernel(*refs):
        pos = 0
        vec_refs = refs[pos:pos + nvec]; pos += nvec
        g_refs = refs[pos:pos + len(g_ops) * n_g_blocks]
        pos += len(g_ops) * n_g_blocks
        w_ref = refs[pos]; pos += 1
        if extra is not None:
            g2_ref, w2_ref = refs[pos], refs[pos + 1]
            pos += 2
        if out_mask is not None:
            yin_ref = refs[pos]; pos += 1
        e_ref = refs[pos]; pos += 1
        stats_ref = refs[pos] if out_mask is not None else None

        i = pl.program_id(2)
        is_first = jnp.logical_and(pl.program_id(1) == 0, i == 0)

        # assemble g (center + halo rows), reconstructing dL/dy per block
        if g_bnbwd is None:
            parts = [g_refs[j][...].astype(jnp.float32)
                     for j in range(n_g_blocks)]
        else:
            consts = tuple(vec_refs[j][...] for j in range(5))
            parts = [_bnbwd_value(g_refs[j][...], g_refs[n_g_blocks + j][...],
                                  consts)
                     for j in range(n_g_blocks)]
        center, halos = parts[0], parts[1:]

        if k == 1:
            gm = center.reshape(nb * th_g * wo, co).astype(dtype)
            m = jnp.dot(gm, w_ref[0, 0], preferred_element_type=jnp.float32)
            if stride == 1:
                t = m.reshape(nb, th_in, wd, bci)
            else:
                m4 = m.reshape(nb, th_g, wo, bci)
                t = _interleave_zeros(
                    _interleave_zeros(m4, axis=2, offset=0), axis=1, offset=0)
        else:
            if stride == 1:
                top = jnp.where(i == 0, jnp.zeros_like(halos[0]), halos[0])
                bot = jnp.where(i == pl.num_programs(2) - 1,
                                jnp.zeros_like(halos[1]), halos[1])
                gin = jnp.concatenate([top, center, bot], axis=1)
                gp = _pad_w(gin.astype(dtype))
                t = _nine_shift_matmul(gp, w_ref, th_in, wd, 1)
                t = t.reshape(nb, th_in, wd, bci)
            else:
                # transposed conv via zero-stuffing: gz[2h+1-P0, 2w+1] =
                # g[h, w] on a (th_in+2, W+2) tile; then a plain 3x3 s1
                # sweep with the flipped kernel (see derivation in tests)
                bot = jnp.where(i == pl.num_programs(2) - 1,
                                jnp.zeros_like(halos[0]), halos[0])
                g_ext = jnp.concatenate([center, bot], axis=1)  # (nb,th_g+1,)
                rows = _interleave_zeros(g_ext, axis=1, offset=1)
                z = _interleave_zeros(rows, axis=2, offset=1)
                z = jnp.concatenate(
                    [z, jnp.zeros((nb, z.shape[1], 2, co), z.dtype)], axis=2)
                t = _nine_shift_matmul(z.astype(dtype), w_ref, th_in, wd, 1)
                t = t.reshape(nb, th_in, wd, bci)

        if extra is not None:
            g2v = g2_ref[...]
            s2 = extra[2]
            m2 = jnp.dot(g2v.reshape(-1, co2).astype(dtype), w2_ref[...],
                         preferred_element_type=jnp.float32)
            if s2 == 1:
                t = t + m2.reshape(nb, th_in, wd, bci)
            else:
                m4 = m2.reshape(nb, th_in // s2, wd // s2, bci)
                t = t + _interleave_zeros(
                    _interleave_zeros(m4, axis=2, offset=0), axis=1, offset=0)

        if out_mask is None:
            e_ref[...] = t.astype(dtype)
        else:
            gmma = vec_refs[n_gvec][...]
            beta = vec_refs[n_gvec + 1][...]
            mu = vec_refs[n_gvec + 2][...]
            inv = vec_refs[n_gvec + 3][...]
            xhat = (yin_ref[...].astype(jnp.float32) - mu) * inv
            mask = (gmma * xhat + beta) > 0
            e_out = jnp.where(mask, t, 0.0)
            e_ref[...] = e_out.astype(dtype)
            ef = e_out.reshape(nb * th_in * wd, bci)
            xf = xhat.reshape(nb * th_in * wd, bci)
            s = jnp.stack([jnp.sum(ef, axis=0), jnp.sum(ef * xf, axis=0)])
            _accumulate_out(stats_ref, s, is_first)

    out = pl.pallas_call(
        kernel,
        grid=(cib, nbb, ht),
        in_specs=in_specs,
        out_specs=out_specs if out_mask is not None else out_specs[0],
        out_shape=out_shapes if out_mask is not None else out_shapes[0],
        compiler_params=_dim_semantics(accumulates=out_mask is not None),
        interpret=_need_interpret(interpret),
    )(*operands)
    return (out[0], out[1]) if out_mask is not None else (out, None)


# ---------------------------------------------------------------------------
# bottleneck-unit composition (ResNet v2 pre-activation), custom VJP
# ---------------------------------------------------------------------------
def _bn_consts(gamma, beta, mean, inv):
    """Fold (gamma, beta, mean, inv_sigma) into apply (scale, bias)."""
    scale = gamma.astype(jnp.float32) * inv
    bias = beta.astype(jnp.float32) - mean * scale
    return scale, bias


def _finalize_stats(stats, count, eps):
    mean = stats[0] / count
    var = jnp.maximum(stats[1] / count - mean * mean, 0.0)
    return mean, var, jax.lax.rsqrt(var + eps)


def _unit_fwd(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
              stride, eps, interpret, axis=None, axis_size=1):
    """Training forward. Weights HWIO; data NHWC. Returns out, batch
    stats (mean/var per BN), and the VJP residuals.

    ``axis``: when run inside ``shard_map`` with the batch sharded over
    mesh axes ``axis``, the BN statistic sums are psum'd over it (global
    batch statistics — the same semantics the unfused pjit graph gets
    from XLA partitioning its batch reductions) and counts are scaled by
    the static ``axis_size``.
    """
    n, h, wd, _ci = data.shape
    n1 = n * h * wd * axis_size
    xf = data.astype(jnp.float32)
    s0 = jnp.sum(xf, axis=(0, 1, 2))
    s1 = jnp.sum(xf * xf, axis=(0, 1, 2))
    s01 = jnp.stack([s0, s1])
    if axis is not None:
        s01 = jax.lax.psum(s01, axis)
    mean1, var1, inv1 = _finalize_stats(s01, n1, eps)
    sc1, bi1 = _bn_consts(g1, b1, mean1, inv1)

    y1, st1 = conv_fwd(data, w1, stride=1, prologue=(sc1, bi1, True),
                       emit_stats=True, interpret=interpret)
    if axis is not None:
        st1 = jax.lax.psum(st1, axis)
    mean2, var2, inv2 = _finalize_stats(st1, n1, eps)
    sc2, bi2 = _bn_consts(g2, b2, mean2, inv2)

    y2, st2 = conv_fwd(y1, w2, stride=stride, prologue=(sc2, bi2, True),
                       emit_stats=True, interpret=interpret)
    if axis is not None:
        st2 = jax.lax.psum(st2, axis)
    n2 = n * (h // stride) * (wd // stride) * axis_size
    mean3, var3, inv3 = _finalize_stats(st2, n2, eps)
    sc3, bi3 = _bn_consts(g3, b3, mean3, inv3)

    y3, _ = conv_fwd(y2, w3, stride=1, prologue=(sc3, bi3, True),
                     emit_stats=False, interpret=interpret)
    if wsc is None:
        shortcut = data
    else:
        shortcut, _ = conv_fwd(data, wsc, stride=stride,
                               prologue=(sc1, bi1, True), interpret=interpret)
    out = y3 + shortcut
    stats = (mean1, var1, mean2, var2, mean3, var3)
    res = (data, y1, y2, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
           mean1, inv1, mean2, inv2, mean3, inv3)
    return out, stats, res


def _unit_bwd(stride, eps, interpret, res, g, axis=None, axis_size=1):
    (data, y1, y2, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
     mean1, inv1, mean2, inv2, mean3, inv3) = res
    n, h, wd, _ci = data.shape
    n1 = float(n * h * wd * axis_size)
    n2 = float(n * (h // stride) * (wd // stride) * axis_size)

    def _allreduce(v):
        return v if axis is None else jax.lax.psum(v, axis)

    sc1, bi1 = _bn_consts(g1, b1, mean1, inv1)
    sc2, bi2 = _bn_consts(g2, b2, mean2, inv2)
    sc3, bi3 = _bn_consts(g3, b3, mean3, inv3)

    # conv3 (1x1 s1): dgrad emits e2 = mask3 * dact3 and (dbeta3, dgamma3)
    e2, st3 = conv_dgrad(g, w3, y2.shape, stride=1,
                         out_mask=(y2, g3, b3, mean3, inv3),
                         interpret=interpret)
    st3 = _allreduce(st3)
    dbeta3, dgamma3 = st3[0], st3[1]
    dw3 = conv_wgrad(y2, g, w3.shape, stride=1,
                     x_prologue=(sc3, bi3, True), interpret=interpret)
    cb2 = (g3.astype(jnp.float32) * inv3, mean3, inv3,
           dbeta3 / n2, dgamma3 / n2)

    # conv2 (3x3, stride): g side reconstructed from (e2, y2) via bn3 bwd
    dw2 = conv_wgrad(y1, (e2, y2), w2.shape, stride=stride,
                     x_prologue=(sc2, bi2, True), g_bnbwd=cb2,
                     interpret=interpret)
    e1, st2 = conv_dgrad((e2, y2), w2, y1.shape, stride=stride, g_bnbwd=cb2,
                         out_mask=(y1, g2, b2, mean2, inv2),
                         interpret=interpret)
    st2 = _allreduce(st2)
    dbeta2, dgamma2 = st2[0], st2[1]
    cb1 = (g2.astype(jnp.float32) * inv2, mean2, inv2,
           dbeta2 / n1, dgamma2 / n1)

    # conv1 (1x1 s1): the downsample shortcut joins at act1 (extra term)
    dw1 = conv_wgrad(data, (e1, y1), w1.shape, stride=1,
                     x_prologue=(sc1, bi1, True), g_bnbwd=cb1,
                     interpret=interpret)
    extra = None if wsc is None else (g, wsc, stride)
    e0, st1 = conv_dgrad((e1, y1), w1, data.shape, stride=1, g_bnbwd=cb1,
                         out_mask=(data, g1, b1, mean1, inv1), extra=extra,
                         interpret=interpret)
    st1 = _allreduce(st1)
    dbeta1, dgamma1 = st1[0], st1[1]

    # weight grads: each shard holds its batch slice's contribution;
    # under shard_map the all-reduce happens here (f32, pre-cast) so the
    # replicated out_specs of the spmd wrapper are genuinely replicated
    dw1, dw2, dw3 = _allreduce(dw1), _allreduce(dw2), _allreduce(dw3)
    dwsc = None
    if wsc is not None:
        dwsc = _allreduce(conv_wgrad(
            data, g, wsc.shape, stride=stride,
            x_prologue=(sc1, bi1, True),
            interpret=interpret)).astype(wsc.dtype)

    # bn1 backward to the unit input (elementwise; XLA fuses it with the
    # dim-match shortcut add)
    xhat0 = (data.astype(jnp.float32) - mean1) * inv1
    ddata = (g1.astype(jnp.float32) * inv1) * (
        e0.astype(jnp.float32) - dbeta1 / n1 - xhat0 * (dgamma1 / n1))
    if wsc is None:
        ddata = ddata + g.astype(jnp.float32)
    ddata = ddata.astype(data.dtype)

    return (ddata, dw1.astype(w1.dtype), dw2.astype(w2.dtype),
            dw3.astype(w3.dtype), dwsc,
            dgamma1.astype(g1.dtype), dbeta1.astype(b1.dtype),
            dgamma2.astype(g2.dtype), dbeta2.astype(b2.dtype),
            dgamma3.astype(g3.dtype), dbeta3.astype(b3.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13))
def bottleneck_train(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
                     stride, eps, interpret):
    """Fused pre-activation bottleneck unit, training mode.

    Returns (out, (mean1, var1, mean2, var2, mean3, var3)) — the batch
    statistics feed the caller's moving-stat update (stop-gradient
    them; they carry no cotangent).
    """
    out, stats, _ = _unit_fwd(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
                              stride, eps, interpret)
    return out, stats


def _bottleneck_train_fwd(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
                          stride, eps, interpret):
    out, stats, res = _unit_fwd(data, w1, w2, w3, wsc, g1, b1, g2, b2,
                                g3, b3, stride, eps, interpret)
    return (out, stats), res


def _bottleneck_train_bwd(stride, eps, interpret, res, cotangents):
    g, _gstats = cotangents
    return _unit_bwd(stride, eps, interpret, res, g)


bottleneck_train.defvjp(_bottleneck_train_fwd, _bottleneck_train_bwd)


def bottleneck_infer(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
                     mm1, mv1, mm2, mv2, mm3, mv3, *, stride, eps,
                     interpret=None):
    """Inference mode: BN applies use the moving statistics."""
    def consts(gm, bt, mm, mv):
        inv = jax.lax.rsqrt(mv.astype(jnp.float32) + eps)
        return _bn_consts(gm, bt, mm.astype(jnp.float32), inv)

    p1 = consts(g1, b1, mm1, mv1) + (True,)
    y1, _ = conv_fwd(data, w1, stride=1, prologue=p1, interpret=interpret)
    p2 = consts(g2, b2, mm2, mv2) + (True,)
    y2, _ = conv_fwd(y1, w2, stride=stride, prologue=p2, interpret=interpret)
    p3 = consts(g3, b3, mm3, mv3) + (True,)
    y3, _ = conv_fwd(y2, w3, stride=1, prologue=p3, interpret=interpret)
    if wsc is None:
        shortcut = data
    else:
        shortcut, _ = conv_fwd(data, wsc, stride=stride, prologue=p1,
                               interpret=interpret)
    return y3 + shortcut


# ---------------------------------------------------------------------------
# multi-chip: explicit shard_map partitioning of the Pallas kernels
# ---------------------------------------------------------------------------
# pjit can freely partition the *interpret-mode* fused graph (it is plain
# jax ops), but real Mosaic kernels are opaque to the partitioner: on TPU
# the batch-sharded fused step must place each kernel inside shard_map
# with the batch axis manual. The wrappers below do that with an explicit
# custom VJP — fwd and bwd are each their own shard_map region, and every
# cross-shard reduction (BN statistic sums, weight grads) is an explicit
# psum over the data axes, so ``check_rep=False`` is sound. Reference
# counterpart of the reduction this replaces: src/kvstore/comm.h:484-690
# (device-tree gradient reduce); here it rides ICI inside the step.

_SPMD_SCOPE = threading.local()


@contextlib.contextmanager
def spmd_scope(mesh, axes):
    """Trace-time marker: fused ops built inside this scope partition
    their Pallas kernels over ``mesh`` with the batch sharded on mesh
    axes ``axes`` (via shard_map). Set by TrainStep around its step
    invocation; consulted by ops/fused.py at trace time."""
    prev = getattr(_SPMD_SCOPE, "value", None)
    _SPMD_SCOPE.value = (mesh, tuple(axes))
    try:
        yield
    finally:
        _SPMD_SCOPE.value = prev


def current_spmd_scope():
    return getattr(_SPMD_SCOPE, "value", None)


def _spmd_parts(mesh, axes):
    ax = tuple(axes)
    asize = int(_np.prod([mesh.shape[a] for a in ax]))
    dspec = P(ax if len(ax) > 1 else ax[0], None, None, None)
    return ax, asize, dspec


_RES_NSHARDED = 3   # res = (data, y1, y2, then 16 replicated leaves)
_RES_NREP = 16


def _res_specs(dspec):
    return (dspec,) * _RES_NSHARDED + (P(),) * _RES_NREP


@functools.partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13, 14, 15))
def bottleneck_train_spmd(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
                          stride, eps, interpret, mesh, axes):
    """``bottleneck_train`` with the batch sharded over mesh ``axes``.

    Same math and return convention as :func:`bottleneck_train` with
    global-batch BN statistics (matching what XLA's partitioner gives
    the unfused graph); out is sharded like data, stats/weight grads
    are replicated.
    """
    (out, stats), _ = _spmd_train_fwd(data, w1, w2, w3, wsc, g1, b1, g2, b2,
                                      g3, b3, stride, eps, interpret, mesh,
                                      axes)
    return out, stats


def _spmd_train_fwd(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
                    stride, eps, interpret, mesh, axes):
    ax, asize, dspec = _spmd_parts(mesh, axes)
    rep = P()

    def local(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3):
        return _unit_fwd(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
                         stride, eps, interpret, axis=ax, axis_size=asize)

    f = _shard_map(
        local, mesh=mesh,
        in_specs=(dspec,) + (rep,) * 10,
        out_specs=(dspec, (rep,) * 6, _res_specs(dspec)),
        check_vma=False)
    out, stats, res = f(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3)
    return (out, stats), res


def _spmd_train_bwd(stride, eps, interpret, mesh, axes, res, cotangents):
    g, _gstats = cotangents
    ax, asize, dspec = _spmd_parts(mesh, axes)
    rep = P()

    def local(res, g):
        return _unit_bwd(stride, eps, interpret, res, g,
                         axis=ax, axis_size=asize)

    f = _shard_map(
        local, mesh=mesh,
        in_specs=(_res_specs(dspec), dspec),
        out_specs=(dspec,) + (rep,) * 10,
        check_vma=False)
    return f(res, g)


bottleneck_train_spmd.defvjp(_spmd_train_fwd, _spmd_train_bwd)


def bottleneck_infer_spmd(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
                          mm1, mv1, mm2, mv2, mm3, mv3, *, stride, eps,
                          mesh, axes, interpret=None):
    """``bottleneck_infer`` with the batch sharded over mesh ``axes``.

    Inference uses the moving statistics, so the computation is purely
    per-sample: a plain forward shard_map with no collectives."""
    _ax, _asize, dspec = _spmd_parts(mesh, axes)
    rep = P()

    def local(*args):
        return bottleneck_infer(*args, stride=stride, eps=eps,
                                interpret=interpret)

    f = _shard_map(local, mesh=mesh,
                      in_specs=(dspec,) + (rep,) * 16,
                      out_specs=dspec, check_vma=False)
    return f(data, w1, w2, w3, wsc, g1, b1, g2, b2, g3, b3,
             mm1, mv1, mm2, mv2, mm3, mv3)
