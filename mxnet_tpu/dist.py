"""Multi-host distributed runtime (the ps-lite/tracker replacement).

Reference counterpart: ps-lite worker/server/scheduler over ZeroMQ +
dmlc tracker (SURVEY §2.4, §5.8: kvstore_dist.h, tools/launch.py). The
TPU-native design has **no server processes**: every worker process joins
one jax.distributed job (GRPC coordinator = the scheduler's rendezvous
role); all devices form a single global mesh whose outermost axis spans
hosts (DCN), and gradient sync is an XLA all-reduce riding ICI within a
host/slice and DCN across — compiled into the step, not a runtime
service.

Environment (set by tools/launch.py; DMLC_* aliases accepted for
reference-script compatibility):
- MXNET_TPU_COORDINATOR   host:port  (DMLC_PS_ROOT_URI/PORT)
- MXNET_TPU_NUM_WORKERS   int        (DMLC_NUM_WORKER)
- MXNET_TPU_WORKER_RANK   int        (DMLC_WORKER_ID)
"""
from __future__ import annotations

import os

import numpy as np

from .base import MXNetError

_INITIALIZED = False


def env_spec():
    """Read the launcher env; returns (coordinator, num, rank) or None."""
    coord = os.environ.get("MXNET_TPU_COORDINATOR")
    if coord is None and os.environ.get("DMLC_PS_ROOT_URI"):
        if int(os.environ.get("DMLC_NUM_SERVER", "0") or 0) > 0:
            # scheduler topology (tools/launch.py -s S): the root URI is
            # the TRACKER's rendezvous endpoint, not a jax coordinator —
            # joining jax.distributed against it would hang. The
            # parameter-server tier (kvstore_server/tracker) owns this
            # layout; the serverless collective path stays out.
            return None
        coord = "%s:%s" % (os.environ["DMLC_PS_ROOT_URI"],
                           os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
    num = os.environ.get("MXNET_TPU_NUM_WORKERS",
                         os.environ.get("DMLC_NUM_WORKER"))
    rank = os.environ.get("MXNET_TPU_WORKER_RANK",
                          os.environ.get("DMLC_WORKER_ID"))
    if coord is None or num is None or rank is None:
        return None
    return coord, int(num), int(rank)


def init_from_env():
    """jax.distributed.initialize from the launcher env (idempotent).

    Returns True if running multi-process, False for single-process.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    spec = env_spec()
    if spec is None:
        return False
    import jax

    coord, num, rank = spec
    if num <= 1:
        return False
    jax.distributed.initialize(coordinator_address=coord, num_processes=num,
                               process_id=rank)
    _INITIALIZED = True
    return True


def is_initialized():
    return _INITIALIZED


def rank():
    import jax

    return jax.process_index()


def num_workers():
    import jax

    return jax.process_count()


def global_mesh(axes=None):
    """Global mesh over all processes' devices, hosts on the outermost
    axis (DCN) — jax collectives ride DCN across it, ICI within a host.

    axes: {name: size} for the *within-host* layout; a leading "dcn" axis
    of size num_processes is prepended automatically when multi-process
    (and merged into the first data axis by consumers that want one flat
    data-parallel axis)."""
    import jax
    from jax.sharding import Mesh

    nproc = jax.process_count()
    local = jax.local_device_count()
    devices = np.asarray(jax.devices())
    if axes is None:
        axes = {"dp": local}
    sizes = list(axes.values())
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        sizes[sizes.index(-1)] = local // known
    if int(np.prod(sizes)) != local:
        raise MXNetError("global_mesh: axes %r must use all %d local devices"
                         % (axes, local))
    if nproc > 1:
        return Mesh(devices.reshape([nproc] + sizes),
                    ("dcn",) + tuple(axes.keys()))
    return Mesh(devices.reshape(sizes), tuple(axes.keys()))


def _stack_across_workers(value):
    """(mesh, global array): each process's host value on the leading
    worker axis of a (num_workers, ...) stacked array."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = global_mesh()
    axis0 = mesh.axis_names[0]                      # "dcn"
    sh = NamedSharding(mesh, P(axis0))
    nproc = num_workers()
    garr = jax.make_array_from_process_local_data(
        sh, value[None], global_shape=(nproc,) + value.shape)
    return mesh, garr


def allreduce(value, op="sum"):
    """Reduce a host-local numpy array across all worker processes; the
    result is identical (replicated) on every worker.

    This is the KVStore-dist push semantics (kvstore_dist.h Push_ →
    server-side aggregation) as one XLA collective: each process
    contributes its slice of a stacked (num_workers, ...) array and the
    reduction collapses the worker axis. op: "sum" or "max"."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    value = np.asarray(value)
    if num_workers() == 1 or not _INITIALIZED:
        return value
    mesh, garr = _stack_across_workers(value)
    red = {"sum": jnp.sum, "max": jnp.max}[op]
    out = jax.jit(
        lambda x: red(x, axis=0),
        out_shardings=NamedSharding(mesh, P()),
    )(garr)
    return np.asarray(out)


def allgather(value):
    """Gather each worker's host-local array: returns the stacked
    (num_workers, ...) array, identical on every worker (ps-lite
    worker→server key exchange collapsed into one collective)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    value = np.asarray(value)
    if num_workers() == 1 or not _INITIALIZED:
        return value[None]
    mesh, garr = _stack_across_workers(value)
    out = jax.jit(
        lambda x: x,
        out_shardings=NamedSharding(mesh, P()),
    )(garr)
    return np.asarray(out)


def broadcast0(value):
    """Rank-0's array wins everywhere (the reference's kvstore.init
    broadcast semantics): realized as one allreduce where every other
    rank contributes zeros."""
    value = np.asarray(value)
    if num_workers() == 1 or not _INITIALIZED:
        return value
    contrib = value if rank() == 0 else np.zeros_like(value)
    return allreduce(contrib)


def barrier():
    """Block until every worker reaches the barrier (ref
    KVStore::Barrier, kvstore.h:254-311)."""
    if not _INITIALIZED:
        return
    allreduce(np.zeros((1,), np.float32))


# ---------------------------------------------------------------------------
# failure detection (ref: ps-lite heartbeats behind
# include/mxnet/kvstore.h:330-340 get_num_dead_node)
# ---------------------------------------------------------------------------
def _client():
    """The jax coordination-service client (heartbeats live there)."""
    try:
        from jax._src.distributed import global_state

        return getattr(global_state, "client", None)
    except Exception:
        return None


def live_workers():
    """rank → alive? map from the coordination service's own heartbeat
    tracking (the ps-lite heartbeat equivalent). All-alive when running
    single-process or when the service is unreachable."""
    n = num_workers() if _INITIALIZED else 1
    c = _client() if _INITIALIZED else None
    if c is None:
        return {r: True for r in range(n)}
    try:
        live = c.get_live_nodes(list(range(n)))
        return {r: r in live for r in range(n)}
    except Exception:
        return {r: True for r in range(n)}


def get_num_dead_node(node_id=0, timeout=60):
    """Number of dead workers (ref: KVStore::get_num_dead_node,
    kvstore.h:330-340; node_id/timeout kept for API parity — the
    coordination service already applies its own heartbeat timeout)."""
    del node_id, timeout
    return sum(1 for alive in live_workers().values() if not alive)


def exit_barrier(timeout_ms=10000):
    """Best-effort barrier before process exit (ref barrier_before_exit_,
    kvstore.h:290-297): bounded by a timeout so one dead worker cannot
    hang the others' shutdown."""
    if not _INITIALIZED:
        return True
    c = _client()
    if c is None:
        return True
    try:
        c.wait_at_barrier("mxtpu_exit_barrier", timeout_ms)
        return True
    except Exception:
        return False
