"""Host-side async dependency engine (Python front end).

Reference counterpart: ``include/mxnet/engine.h`` Engine API +
``python/mxnet/engine.py`` (bulk control). On TPU the device schedule is
XLA's; this engine orders *host* work — prefetch, checkpoint IO,
callbacks — with the reference's exact var semantics (concurrent readers,
exclusive writers, program order; threaded_engine.h:115-217).

Engines (env ``MXNET_ENGINE_TYPE``, ref src/engine/engine.cc:32-62):
- ``ThreadedEngine`` (default): the native C++ scheduler in
  src/engine.cc via ctypes (workers = ``MXNET_CPU_WORKER_NTHREADS``).
- ``NaiveEngine``: synchronous execute-on-push, the determinism escape
  hatch (ref src/engine/naive_engine.cc).
"""
from __future__ import annotations

import ctypes
import os
import threading

from . import _native
from .base import MXNetError

__all__ = ["Engine", "NaiveEngine", "ThreadedEngine", "get", "create",
           "new_var", "push", "wait_for_var", "wait_for_all",
           "set_bulk_size", "bulk"]


class NaiveEngine:
    """Execute-on-push; trivially respects all dependencies."""

    def __init__(self, num_threads=None):
        self._pushed = 0

    def new_var(self):
        return object()

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        self._pushed += 1
        fn()

    def wait_for_var(self, var):
        pass

    def wait_for_all(self):
        pass

    def stats(self):
        return {"pushed": self._pushed, "executed": self._pushed}


class ThreadedEngine:
    """ctypes front end of the native C++ dependency engine."""

    def __init__(self, num_threads=None):
        lib = _native.get_lib()
        if lib is None:
            raise MXNetError(
                "native runtime unavailable (%s); use NaiveEngine or unset "
                "MXNET_TPU_NO_NATIVE" % (_native.last_error() or "build failed"))
        self._lib = lib
        if num_threads is None:
            num_threads = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS", "4"))
        self._handle = lib.MXTEngineCreate(num_threads)
        self._cb_lock = threading.Lock()
        self._callbacks = {}
        self._next_cb = 1  # keys start at 1: c_void_p(0) arrives as None

        def trampoline(arg):
            key = int(arg)
            with self._cb_lock:
                fn = self._callbacks.pop(key)
            fn()

        self._trampoline = _native.ENGINE_FN(trampoline)

    def __del__(self):
        handle, self._handle = getattr(self, "_handle", None), None
        if handle and self._lib is not None:
            self._lib.MXTEngineFree(handle)

    def new_var(self):
        return self._lib.MXTEngineNewVar(self._handle)

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        with self._cb_lock:
            key = self._next_cb
            self._next_cb += 1
            self._callbacks[key] = fn
        cv = (ctypes.c_int64 * max(len(const_vars), 1))(*const_vars)
        mv = (ctypes.c_int64 * max(len(mutable_vars), 1))(*mutable_vars)
        rc = self._lib.MXTEnginePush(
            self._handle, self._trampoline, ctypes.c_void_p(key),
            cv, len(const_vars), mv, len(mutable_vars), priority)
        if rc != 0:
            with self._cb_lock:
                self._callbacks.pop(key, None)
            raise MXNetError("engine push failed: %s" % _native.last_error())

    def wait_for_var(self, var):
        if self._lib.MXTEngineWaitForVar(self._handle, var) != 0:
            raise MXNetError("wait_for_var failed: %s" % _native.last_error())

    def wait_for_all(self):
        self._lib.MXTEngineWaitAll(self._handle)

    def stats(self):
        pushed = ctypes.c_int64()
        executed = ctypes.c_int64()
        self._lib.MXTEngineStats(self._handle, ctypes.byref(pushed),
                                 ctypes.byref(executed))
        return {"pushed": pushed.value, "executed": executed.value}


Engine = ThreadedEngine

_ENGINE = None
_ENGINE_LOCK = threading.Lock()


def create(kind=None, num_threads=None):
    """Engine factory (ref src/engine/engine.cc CreateEngine)."""
    kind = kind or os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEngine")
    if kind in ("ThreadedEngine", "ThreadedEnginePerDevice"):
        try:
            return ThreadedEngine(num_threads)
        except MXNetError as e:
            # a broken native build must be loud, not a silent slowdown
            # (opting out via MXNET_TPU_NO_NATIVE=1 is intentional: quiet)
            if os.environ.get("MXNET_TPU_NO_NATIVE", "0") != "1":
                import logging

                logging.getLogger("mxnet_tpu").warning(
                    "native ThreadedEngine unavailable (%s); falling back "
                    "to NaiveEngine — rebuild src/ (make -C src) or set "
                    "MXNET_TPU_NO_NATIVE=1 to opt out explicitly", e)
            return NaiveEngine(num_threads)
    if kind == "NaiveEngine":
        return NaiveEngine(num_threads)
    raise MXNetError("unknown engine type %r" % kind)


def get():
    """Process-wide engine singleton (ref Engine::Get)."""
    global _ENGINE
    if _ENGINE is None:
        with _ENGINE_LOCK:
            if _ENGINE is None:
                _ENGINE = create()
    return _ENGINE


def new_var():
    return get().new_var()


def push(fn, const_vars=(), mutable_vars=(), priority=0):
    get().push(fn, const_vars, mutable_vars, priority)


def wait_for_var(var):
    get().wait_for_var(var)


def wait_for_all():
    get().wait_for_all()


# ---- bulk-execution API parity (python/mxnet/engine.py) ----------------
_BULK_SIZE = 0


def set_bulk_size(size):
    """API parity with mx.engine.set_bulk_size. Under XLA the jit trace
    is the bulk segment, so this only records the value."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


class bulk:
    """Context manager parity (python/mxnet/engine.py bulk)."""

    def __init__(self, size):
        self.size = size
        self._old = None

    def __enter__(self):
        self._old = set_bulk_size(self.size)

    def __exit__(self, *exc):
        set_bulk_size(self._old)
