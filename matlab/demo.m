%DEMO classify a synthetic digit with a trained checkpoint.
%
% Reference counterpart: matlab/demo.m (inception classification).
% Train any model with the python frontend and save a checkpoint,
% e.g.:
%   python examples/image-classification/train_mnist.py
% then:
%   setenv('MXTPU_ROOT', '/path/to/repo')
%   addpath('matlab'); demo

% required environment: MXTPU_ROOT (repo checkout), MXTPU_DEMO_PREFIX
% (checkpoint prefix), MXTPU_DEMO_EPOCH (checkpoint epoch number)
prefix = getenv('MXTPU_DEMO_PREFIX');
assert(~isempty(prefix), 'set MXTPU_DEMO_PREFIX to a checkpoint prefix');
epoch = str2double(getenv('MXTPU_DEMO_EPOCH'));
assert(isfinite(epoch), 'set MXTPU_DEMO_EPOCH to the checkpoint epoch');

m = mxnettpu.model;
m.load(prefix, epoch);

% a batch of one flat 784-pixel image (the mnist MLP input layout)
img = rand(784, 1, 'single');
probs = m.forward(img);
[p, label] = max(probs(:, 1));
fprintf('predicted class %d with probability %.4f\n', label - 1, p);
fprintf('MATLAB_DEMO_OK\n');
