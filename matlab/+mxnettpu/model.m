classdef model < handle
%MODEL mxnet_tpu model: load a checkpoint and run forward.
%
% Reference counterpart: matlab/+mxnet/model.m (the reference's
% matlab binding over the C predict API). Same surface here over
% libmxtpu_predict.so (src/c_predict.cc): load('prefix', epoch)
% reads prefix-symbol.json + prefix-NNNN.params, forward(data)
% returns the output activations. Requires MATLAB's foreign-function
% interface (loadlibrary/calllib — not implemented by GNU Octave,
% same constraint as the reference binding).
%
% Example:
%   addpath('matlab')
%   m = mxnettpu.model;
%   m.load('model/lenet', 12);
%   probs = m.forward(single(img));

properties
% the symbol definition, json format
  symbol
% raw bytes of the params file
  params
% print progress info
  verbose
end

properties (Access = private)
  predictor
  loaded
% input size the live predictor was created for (recreate on change)
  prev_input_size
end

methods
  function obj = model()
  %CONSTRUCTOR
  obj.predictor = libpointer('voidPtr', 0);
  obj.verbose = 1;
  obj.loaded = false;
  obj.prev_input_size = [];
  mxnettpu.model.ensure_lib();
  end

  function delete(obj)
  %DESTRUCTOR
  obj.free_predictor();
  end

  function load(obj, model_prefix, num_epoch)
  %LOAD read prefix-symbol.json and prefix-NNNN.params
  obj.symbol = fileread([model_prefix, '-symbol.json']);
  fid = fopen(sprintf('%s-%04d.params', model_prefix, num_epoch), 'rb');
  assert(fid >= 0, 'cannot open params file');
  obj.params = fread(fid, inf, 'uint8=>uint8');
  fclose(fid);
  obj.free_predictor();
  obj.prev_input_size = [];
  if obj.verbose
    fprintf('loaded %s (%d param bytes)\n', model_prefix, ...
            numel(obj.params));
  end
  obj.loaded = true;
  end

  function out = forward(obj, data)
  %FORWARD run the network on a single-precision input array.
  %
  % data follows the matlab convention of the reference binding:
  % column-major with dims reversed vs the backend row-major shape
  % (an HxWxCxN image batch enters as matlab size [W H C N]).
  assert(obj.loaded, 'call load() first');
  data = single(data);
  siz = size(data);
  % reuse the live predictor while the input size is unchanged
  % (reference pattern: model.m prev_input_size); recreating frees
  % the old handle first so repeated forwards never leak
  if ~isequal(siz, obj.prev_input_size)
    obj.free_predictor();
    cshape = uint32(fliplr(siz));          % backend row-major shape
    indptr = uint32([0, numel(cshape)]);
    keys = {'data'};
    phandle = libpointer('voidPtrPtr', libpointer('voidPtr', 0));
    rc = calllib('libmxtpu_predict', 'MXPredCreate', obj.symbol, ...
                 obj.params, int32(numel(obj.params)), int32(1), ...
                 int32(0), uint32(1), keys, indptr, cshape, phandle);
    mxnettpu.model.check(rc, 'MXPredCreate');
    obj.predictor = phandle.Value;
    obj.prev_input_size = siz;
  end

  rc = calllib('libmxtpu_predict', 'MXPredSetInput', obj.predictor, ...
               'data', data(:), uint32(numel(data)));
  mxnettpu.model.check(rc, 'MXPredSetInput');

  rc = calllib('libmxtpu_predict', 'MXPredForward', obj.predictor);
  mxnettpu.model.check(rc, 'MXPredForward');

  % output 0 shape
  pdim = libpointer('uint32Ptr', uint32(0));
  pshape = libpointer('uint32PtrPtr', libpointer('uint32Ptr', uint32(0)));
  rc = calllib('libmxtpu_predict', 'MXPredGetOutputShape', ...
               obj.predictor, uint32(0), pshape, pdim);
  mxnettpu.model.check(rc, 'MXPredGetOutputShape');
  ndim = double(pdim.Value);
  setdatatype(pshape.Value, 'uint32Ptr', ndim);
  oshape = double(pshape.Value.Value(1:ndim));
  n = prod(oshape);

  pout = libpointer('singlePtr', zeros(n, 1, 'single'));
  rc = calllib('libmxtpu_predict', 'MXPredGetOutput', obj.predictor, ...
               uint32(0), pout, uint32(n));
  mxnettpu.model.check(rc, 'MXPredGetOutput');
  % backend row-major -> matlab column-major with reversed dims
  out = reshape(pout.Value, fliplr(oshape));
  end
end

methods (Access = private)
  function free_predictor(obj)
  if obj.predictor.Value ~= 0
    calllib('libmxtpu_predict', 'MXPredFree', obj.predictor);
    obj.predictor = libpointer('voidPtr', 0);
  end
  end
end

methods (Static)
  function ensure_lib()
  if ~libisloaded('libmxtpu_predict')
    root = getenv('MXTPU_ROOT');
    assert(~isempty(root), 'set MXTPU_ROOT to the repo checkout');
    sofile = fullfile(root, 'mxnet_tpu', 'lib', 'libmxtpu_predict.so');
    header = fullfile(root, 'src', 'c_predict_api.h');
    loadlibrary(sofile, header);
  end
  end

  function check(rc, name)
  if rc ~= 0
    err = calllib('libmxtpu_predict', 'MXGetLastError');
    error('%s failed: %s', name, err);
  end
  end
end
end
