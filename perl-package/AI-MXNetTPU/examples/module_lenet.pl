#!/usr/bin/perl
# LeNet on MNIST through AI::MXNetTPU::Module — the Module-tier flow
# (fit/score/predict) in pure Perl.
#
# Reference counterpart: perl-package/AI-MXNet/examples/mnist.pl with
# AI::MXNet::Module (itself module/module.py's loop). Usage:
#   module_lenet.pl <train-images-file> <train-labels-file>
# Prints PERL_MODULE_OK when final accuracy >= 0.95.
use strict;
use warnings;
use FindBin;
use lib "$FindBin::Bin/../lib";
use lib "$FindBin::Bin/../blib/lib";
use lib "$FindBin::Bin/../blib/arch";
use AI::MXNetTPU;
use AI::MXNetTPU::Module;

my ( $images, $labels ) = @ARGV;
die "usage: $0 <images> <labels>\n" unless $labels;

srand(7);

my $it = AI::MXNetTPU::IO->new(
    'MNISTIter',
    image      => $images,
    label      => $labels,
    batch_size => 32,
    flat       => 'False',
    shuffle    => 'False',
);

# LeNet (example/image-classification/symbols/lenet.py parity, sizes
# trimmed for CI): conv-tanh-pool x2 -> fc-tanh -> fc -> softmax
my $S    = 'AI::MXNetTPU::Symbol';
my $data = $S->variable('data');
my $c1 = $S->create( 'Convolution', { kernel => '(5,5)', num_filter => 8 },
    { data => $data }, 'conv1' );
my $a1 = $S->create( 'Activation', { act_type => 'tanh' }, { data => $c1 },
    'tanh1' );
my $p1 = $S->create( 'Pooling',
    { pool_type => 'max', kernel => '(2,2)', stride => '(2,2)' },
    { data => $a1 }, 'pool1' );
my $c2 = $S->create( 'Convolution', { kernel => '(5,5)', num_filter => 16 },
    { data => $p1 }, 'conv2' );
my $a2 = $S->create( 'Activation', { act_type => 'tanh' }, { data => $c2 },
    'tanh2' );
my $p2 = $S->create( 'Pooling',
    { pool_type => 'max', kernel => '(2,2)', stride => '(2,2)' },
    { data => $a2 }, 'pool2' );
my $fl = $S->create( 'Flatten', {}, { data => $p2 }, 'flatten' );
my $f1 = $S->create( 'FullyConnected', { num_hidden => 64 },
    { data => $fl }, 'fc1' );
my $a3 = $S->create( 'Activation', { act_type => 'tanh' }, { data => $f1 },
    'tanh3' );
my $f2 = $S->create( 'FullyConnected', { num_hidden => 10 },
    { data => $a3 }, 'fc2' );
my $net = $S->create( 'SoftmaxOutput', {}, { data => $f2 }, 'softmax' );

my $mod = AI::MXNetTPU::Module->new( symbol => $net );
$mod->fit(
    $it,
    num_epoch        => 6,
    optimizer_params => { learning_rate => 0.1, momentum => 0.9 },
);

my $acc = $mod->score($it);
printf( "final accuracy: %.4f\n", $acc );
die "accuracy $acc below bar\n" unless $acc >= 0.95;

# predict must agree with score: same probs, so same argmax accuracy
my $probs = $mod->predict($it);
my @labels;
$it->reset;
while ( $it->next ) { push @labels, @{ $it->label->aslist }; }
die "predict size mismatch\n" unless @$probs == @labels * 10;
my $hit = 0;
for my $i ( 0 .. $#labels ) {
    my ( $best, $bp ) = ( 0, -1 );
    for my $c ( 0 .. 9 ) {
        my $v = $probs->[ $i * 10 + $c ];
        ( $best, $bp ) = ( $c, $v ) if $v > $bp;
    }
    $hit++ if $best == int( $labels[$i] );
}
my $pacc = $hit / @labels;
die sprintf( "predict acc %.4f != score acc %.4f\n", $pacc, $acc )
  if abs( $pacc - $acc ) > 1e-9;

print "PERL_MODULE_OK\n";
