#!/usr/bin/perl
# MNIST softmax regression in pure Perl through libmxtpu_c_api.so.
#
# Reference counterpart: perl-package/AI-MXNet/examples/mnist.pl — the
# same flow (MNISTIter -> symbol -> executor -> sgd_update) with no
# Python in the consumer. Usage:
#   train_mnist.pl <train-images-file> <train-labels-file>
use strict;
use warnings;
use FindBin;
use lib "$FindBin::Bin/../lib";
use lib "$FindBin::Bin/../blib/lib";
use lib "$FindBin::Bin/../blib/arch";
use AI::MXNetTPU;

my ( $images, $labels ) = @ARGV;
die "usage: $0 <images> <labels>\n" unless $labels;

my $batch = 32;
my $it    = AI::MXNetTPU::IO->new(
    'MNISTIter',
    image      => $images,
    label      => $labels,
    batch_size => $batch,
    flat       => 'True',
    shuffle    => 'False',
);

my $data  = AI::MXNetTPU::Symbol->variable('data');
my $label = AI::MXNetTPU::Symbol->variable('softmax_label');
my $fc    = AI::MXNetTPU::Symbol->create( 'FullyConnected',
    { num_hidden => 10 }, { data => $data }, 'fc' );
my $net = AI::MXNetTPU::Symbol->create( 'SoftmaxOutput', {},
    { data => $fc, label => $label }, 'softmax' );

my $exe = AI::MXNetTPU::Executor->simple_bind( $net,
    { data => [ $batch, 784 ], softmax_label => [$batch] } );

my $args  = $exe->arg_dict;
my $grads = $exe->grad_dict;

# tiny deterministic init
{
    my $w = $args->{fc_weight};
    my $n = $w->size;
    $w->set( [ map { ( ( $_ * 37 ) % 101 - 50 ) / 5000.0 } 0 .. $n - 1 ] );
    $args->{fc_bias}->set( [ (0) x $args->{fc_bias}->size ] );
}

my $acc = 0;
for my $epoch ( 0 .. 11 ) {
    $it->reset;
    my ( $correct, $total ) = ( 0, 0 );
    while ( $it->next ) {
        $args->{data}->copy_from( $it->data );
        $args->{softmax_label}->copy_from( $it->label );
        my $outs = $exe->forward(1);
        $exe->backward;
        for my $p (qw(fc_weight fc_bias)) {
            $args->{$p}->sgd_update( $grads->{$p},
                lr => 0.1, rescale_grad => 1.0 / $batch );
        }
        my $probs = $outs->[0]->aslist;
        my $labs  = $it->label->aslist;
        for my $i ( 0 .. $batch - 1 ) {
            my ( $best, $bp ) = ( 0, -1 );
            for my $c ( 0 .. 9 ) {
                my $v = $probs->[ $i * 10 + $c ];
                ( $best, $bp ) = ( $c, $v ) if $v > $bp;
            }
            $correct++ if $best == int( $labs->[$i] );
            $total++;
        }
    }
    $acc = $correct / $total;
    printf "epoch %d accuracy %.3f\n", $epoch, $acc;
}

die "final accuracy $acc too low\n" if $acc < 0.85;
print "PERL_MNIST_OK acc=$acc\n";
AI::MXNetTPU::notify_shutdown();
