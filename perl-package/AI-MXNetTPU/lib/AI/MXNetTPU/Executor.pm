package AI::MXNetTPU::Executor;

# Executor surface (ref: perl-package/AI-MXNet/lib/AI/MXNet/Executor.pm)
# over MXExecutorSimpleBind/Forward/Backward/Outputs.

use strict;
use warnings;
use AI::MXNetTPU;
use AI::MXNetTPU::NDArray;

sub simple_bind {
    my ( $class, $symbol, $shapes ) = @_;
    my ( @names, @data, @idx );
    push @idx, 0;
    for my $n ( sort keys %$shapes ) {
        push @names, $n;
        push @data,  @{ $shapes->{$n} };
        push @idx,   scalar(@data);
    }
    my ( $exe, $in_args, $arg_grads, $aux ) =
      AI::MXNetTPU::executor_simple_bind( $symbol->handle, \@names, \@data,
        \@idx );
    my $self = bless {
        handle    => $exe,
        symbol    => $symbol,
        arg_names => $symbol->list_arguments,
    }, $class;
    # SimpleBind transfers handle ownership to the caller
    $self->{in_args} =
      [ map { AI::MXNetTPU::NDArray->new_from_handle($_) } @$in_args ];
    $self->{arg_grads} = [
        map {
            defined($_)
              ? AI::MXNetTPU::NDArray->new_from_handle($_)
              : undef
        } @$arg_grads
    ];
    $self->{aux} =
      [ map { AI::MXNetTPU::NDArray->new_from_handle($_) } @$aux ];
    return $self;
}

sub arg_dict {
    my ($self) = @_;
    my %d;
    @d{ @{ $self->{arg_names} } } = @{ $self->{in_args} };
    return \%d;
}

sub grad_dict {
    my ($self) = @_;
    my %d;
    @d{ @{ $self->{arg_names} } } = @{ $self->{arg_grads} };
    return \%d;
}

sub forward {
    my ( $self, $is_train ) = @_;
    AI::MXNetTPU::executor_forward( $self->{handle}, $is_train ? 1 : 0 );
    # ExecutorOutputs transfers ownership: freed when the wrappers drop
    return [ map { AI::MXNetTPU::NDArray->new_from_handle($_) }
          AI::MXNetTPU::executor_outputs( $self->{handle} ) ];
}

sub backward {
    my ($self) = @_;
    AI::MXNetTPU::executor_backward( $self->{handle} );
}

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::executor_free( $self->{handle} ) if $self->{handle};
}

1;
