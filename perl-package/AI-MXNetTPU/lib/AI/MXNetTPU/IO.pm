package AI::MXNetTPU::IO;

# Data-iterator surface (ref: perl-package/AI-MXNet/lib/AI/MXNet/IO.pm)
# over the MXDataIter* ABI (MNISTIter, CSVIter, ImageRecordIter, ...).

use strict;
use warnings;
use AI::MXNetTPU;
use AI::MXNetTPU::NDArray;

sub new {
    my ( $class, $iter_name, %params ) = @_;
    my @keys = sort keys %params;
    my $h    = AI::MXNetTPU::dataiter_create( $iter_name, \@keys,
        [ map { "" . $params{$_} } @keys ] );
    return bless { handle => $h }, $class;
}

sub reset { AI::MXNetTPU::dataiter_before_first( $_[0]{handle} ) }

sub next { AI::MXNetTPU::dataiter_next( $_[0]{handle} ) }

# GetData/GetLabel return caller-owned handles (c_api.cc ownership
# contract): wrap owned so DESTROY frees them per batch
sub data {
    AI::MXNetTPU::NDArray->new_from_handle(
        AI::MXNetTPU::dataiter_data( $_[0]{handle} ) );
}

sub label {
    AI::MXNetTPU::NDArray->new_from_handle(
        AI::MXNetTPU::dataiter_label( $_[0]{handle} ) );
}

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::dataiter_free( $self->{handle} ) if $self->{handle};
}

1;
