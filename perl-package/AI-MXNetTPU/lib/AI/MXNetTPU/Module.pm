package AI::MXNetTPU::Module;

# Module tier: bind / init_params / fit / score / predict over the
# executor + imperative-optimizer ABI.
#
# Reference counterpart: perl-package/AI-MXNet/lib/AI/MXNet/Module.pm
# (itself mirroring python/mxnet/module/module.py) — the same high-level
# training loop, minus the multi-device executor group (the TPU stack
# scales through the fused SPMD step on the python tier).
#
#   my $mod = AI::MXNetTPU::Module->new( symbol => $net );
#   $mod->fit( $train_iter,
#       num_epoch        => 10,
#       optimizer_params => { learning_rate => 0.1, momentum => 0.9 } );
#   my $acc = $mod->score($val_iter);

use strict;
use warnings;
use AI::MXNetTPU;
use AI::MXNetTPU::NDArray;
use AI::MXNetTPU::Symbol;
use AI::MXNetTPU::Executor;

sub new {
    my ( $class, %args ) = @_;
    my $symbol = $args{symbol} or die "Module->new: symbol required\n";
    my $self   = bless {
        symbol      => $symbol,
        data_name   => $args{data_name}  // 'data',
        label_name  => $args{label_name} // 'softmax_label',
        binded      => 0,
        params_init => 0,
    }, $class;
    return $self;
}

sub symbol { $_[0]{symbol} }

# ---- bind -----------------------------------------------------------------

sub bind {
    my ( $self, %shapes ) = @_;
    die "Module->bind: data shape required\n"
      unless $shapes{ $self->{data_name} };
    $self->{exec} =
      AI::MXNetTPU::Executor->simple_bind( $self->{symbol}, \%shapes );
    $self->{data_shape}  = [ @{ $shapes{ $self->{data_name} } } ];
    $self->{label_shape} = [ @{ $shapes{ $self->{label_name} } || [] } ];
    $self->{binded}      = 1;
    return $self;
}

# ---- init -----------------------------------------------------------------

# Xavier-uniform over backend-layout fans (initializer.py Xavier parity);
# bias/beta zero, gamma/moving-var one. Deterministic via srand outside.
sub _xavier_fill {
    my ($shape) = @_;
    my $n = 1;
    $n *= $_ for @$shape;
    my $hw = 1;
    $hw *= $shape->[$_] for 2 .. $#$shape;
    my $fan_out = $shape->[0] * $hw;
    my $fan_in  = ( @$shape > 1 ? $shape->[1] : $shape->[0] ) * $hw;
    my $scale   = sqrt( 3.0 / ( ( $fan_in + $fan_out ) / 2.0 ) );
    return [ map { ( rand(2) - 1 ) * $scale } 1 .. $n ];
}

sub init_params {
    my ($self) = @_;
    die "Module->init_params: call bind first\n" unless $self->{binded};
    my $args = $self->{exec}->arg_dict;
    # sort: perl randomizes hash order per process, and the shared rand()
    # stream must be consumed in a stable order for srand() determinism
    for my $name ( sort keys %$args ) {
        next
          if $name eq $self->{data_name}
          or $name eq $self->{label_name};
        my $arr   = $args->{$name};
        my $shape = $arr->shape;
        my $n     = $arr->size;
        if ( $name =~ /(?:bias|beta)$/ ) {
            $arr->set( [ (0) x $n ] );
        }
        elsif ( $name =~ /gamma$/ ) {
            $arr->set( [ (1) x $n ] );
        }
        else {
            $arr->set( _xavier_fill($shape) );
        }
    }
    for my $i ( 0 .. $#{ $self->{exec}{aux} } ) {
        my $name = $self->{symbol}->list_auxiliary_states->[$i] // '';
        my $arr  = $self->{exec}{aux}[$i];
        my $v    = ( $name =~ /var$/ ) ? 1 : 0;
        $arr->set( [ ($v) x $arr->size ] );
    }
    $self->{params_init} = 1;
    return $self;
}

# ---- the train loop -------------------------------------------------------

sub _update {
    my ( $self, %opt ) = @_;
    my $lr       = $opt{learning_rate} // 0.01;
    my $momentum = $opt{momentum}      // 0;
    my $wd       = $opt{wd}            // 0;
    my $rescale  = $opt{rescale_grad}  // 1.0;
    for my $pair ( @{ $self->{update_pairs} } ) {
        my ( $name, $w, $g ) = @$pair;
        if ( $momentum > 0 ) {
            my $m = $self->{momentum_state}{$name};
            AI::MXNetTPU::imperative_invoke(
                'sgd_mom_update',
                [ $w->handle, $g->handle, $m->handle ],
                [ $w->handle ],
                [ 'lr', 'momentum', 'rescale_grad', 'wd' ],
                [ $lr,  $momentum,  $rescale,       $wd ]
            );
        }
        else {
            AI::MXNetTPU::imperative_invoke(
                'sgd_update',
                [ $w->handle,  $g->handle ],
                [ $w->handle ],
                [ 'lr', 'rescale_grad', 'wd' ],
                [ $lr,  $rescale,       $wd ]
            );
        }
    }
}

sub _batch_accuracy {
    my ( $probs, $labels, $n_batch, $n_cls ) = @_;
    my $hit = 0;
    for my $i ( 0 .. $n_batch - 1 ) {
        my ( $best, $bp ) = ( 0, -1 );
        for my $c ( 0 .. $n_cls - 1 ) {
            my $v = $probs->[ $i * $n_cls + $c ];
            ( $best, $bp ) = ( $c, $v ) if $v > $bp;
        }
        $hit++ if $best == int( $labels->[$i] );
    }
    return $hit;
}

sub fit {
    my ( $self, $iter, %args ) = @_;
    my $num_epoch = $args{num_epoch} // 10;
    my %opt       = %{ $args{optimizer_params} || {} };

    # auto-bind from the first batch
    unless ( $self->{binded} ) {
        $iter->reset;
        $iter->next or die "Module->fit: empty iterator\n";
        my ( $ds, $ls ) = ( $iter->data->shape, $iter->label->shape );
        $self->bind(
            $self->{data_name}  => $ds,
            $self->{label_name} => $ls
        );
    }
    $self->init_params unless $self->{params_init};

    my $args_d = $self->{exec}->arg_dict;
    $self->{trainable} = [
        grep { $_ ne $self->{data_name} && $_ ne $self->{label_name} }
          @{ $self->{symbol}->list_arguments }
    ];
    $opt{rescale_grad} //= 1.0 / $self->{data_shape}[0];
    if ( ( $opt{momentum} // 0 ) > 0 ) {
        for my $name ( @{ $self->{trainable} } ) {
            $self->{momentum_state}{$name} =
              AI::MXNetTPU::NDArray->zeros( $args_d->{$name}->shape );
        }
    }
    # resolve (name, weight, grad) once — the dicts are immutable after
    # bind, so rebuilding them per batch in _update is pure waste
    my $grads_d = $self->{exec}->grad_dict;
    $self->{update_pairs} = [
        grep { defined $_->[2] }
        map  { [ $_, $args_d->{$_}, $grads_d->{$_} ] }
          @{ $self->{trainable} }
    ];

    my $last_acc = 0;
    for my $epoch ( 1 .. $num_epoch ) {
        $iter->reset;
        my ( $hit, $seen ) = ( 0, 0 );
        while ( $iter->next ) {
            $args_d->{ $self->{data_name} }->copy_from( $iter->data );
            my $label = $iter->label;
            $args_d->{ $self->{label_name} }->copy_from($label);
            my $outs = $self->{exec}->forward(1);
            $self->{exec}->backward;
            $self->_update(%opt);
            my $labels  = $label->aslist;
            my $n_batch = scalar @$labels;
            my $probs   = $outs->[0]->aslist;
            my $n_cls   = @$probs / $n_batch;
            $hit  += _batch_accuracy( $probs, $labels, $n_batch, $n_cls );
            $seen += $n_batch;
        }
        $last_acc = $seen ? $hit / $seen : 0;
        printf( "Epoch[%d] Train-accuracy=%.4f\n", $epoch, $last_acc )
          unless $args{quiet};
    }
    return $last_acc;
}

# ---- evaluation -----------------------------------------------------------

sub predict {
    my ( $self, $iter ) = @_;
    die "Module->predict: call fit or bind+init first\n"
      unless $self->{binded};
    my $args_d = $self->{exec}->arg_dict;
    my @all;
    $iter->reset;
    while ( $iter->next ) {
        $args_d->{ $self->{data_name} }->copy_from( $iter->data );
        my $outs = $self->{exec}->forward(0);
        push @all, @{ $outs->[0]->aslist };
    }
    return \@all;
}

sub score {
    my ( $self, $iter ) = @_;
    die "Module->score: call fit or bind+init first\n"
      unless $self->{binded};
    my $args_d = $self->{exec}->arg_dict;
    my ( $hit, $seen ) = ( 0, 0 );
    $iter->reset;
    while ( $iter->next ) {
        $args_d->{ $self->{data_name} }->copy_from( $iter->data );
        my $outs   = $self->{exec}->forward(0);
        my $labels = $iter->label->aslist;
        my $probs  = $outs->[0]->aslist;
        my $n      = scalar @$labels;
        $hit  += _batch_accuracy( $probs, $labels, $n, @$probs / $n );
        $seen += $n;
    }
    return $seen ? $hit / $seen : 0;
}

sub get_params {
    my ($self) = @_;
    my $args = $self->{exec}->arg_dict;
    my %out;
    for my $name ( @{ $self->{trainable} || [] } ) {
        $out{$name} = $args->{$name}->aslist;
    }
    return \%out;
}

sub set_params {
    my ( $self, $params ) = @_;
    my $args = $self->{exec}->arg_dict;
    for my $name ( keys %$params ) {
        $args->{$name}->set( $params->{$name} ) if $args->{$name};
    }
    return $self;
}

1;
