package AI::MXNetTPU::Symbol;

# Symbol surface (ref: perl-package/AI-MXNet/lib/AI/MXNet/Symbol.pm):
# compose graph nodes through the atomic-symbol ABI.

use strict;
use warnings;
use AI::MXNetTPU;

sub new_from_handle {
    my ( $class, $handle ) = @_;
    return bless { handle => $handle }, $class;
}

sub variable {
    my ( $class, $name ) = @_;
    return $class->new_from_handle( AI::MXNetTPU::sym_variable($name) );
}

# Symbol->create('FullyConnected', {num_hidden=>10}, {data=>$sym}, 'fc1')
sub create {
    my ( $class, $op, $attrs, $inputs, $name ) = @_;
    $attrs  //= {};
    $inputs //= {};
    $name   //= lc($op);
    my @keys = sort keys %$attrs;
    my $h    = AI::MXNetTPU::sym_create( $op, \@keys,
        [ map { "" . $attrs->{$_} } @keys ] );
    my @in_names = sort keys %$inputs;
    AI::MXNetTPU::sym_compose( $h, $name, \@in_names,
        [ map { $inputs->{$_}{handle} } @in_names ] );
    return $class->new_from_handle($h);
}

sub handle { $_[0]{handle} }

sub list_arguments {
    return [ AI::MXNetTPU::sym_list_arguments( $_[0]{handle} ) ];
}

sub list_auxiliary_states {
    return [ AI::MXNetTPU::sym_list_aux( $_[0]{handle} ) ];
}

sub tojson { AI::MXNetTPU::sym_to_json( $_[0]{handle} ) }

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::sym_free( $self->{handle} ) if $self->{handle};
}

1;
