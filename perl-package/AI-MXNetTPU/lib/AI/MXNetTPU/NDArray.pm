package AI::MXNetTPU::NDArray;

# NDArray surface (ref: perl-package/AI-MXNet/lib/AI/MXNet/NDArray.pm).
# Tensors cross the ABI as packed float32 strings (pack 'f*').

use strict;
use warnings;
use AI::MXNetTPU;

sub new_from_handle {
    my ( $class, $handle, $owned ) = @_;
    return bless { handle => $handle, owned => ( $owned // 1 ) }, $class;
}

# AI::MXNetTPU::NDArray->array([...values...], [shape])
sub array {
    my ( $class, $values, $shape ) = @_;
    $shape //= [ scalar @$values ];
    my $h = AI::MXNetTPU::nd_create( $shape, 0 );    # dtype 0 = float32
    AI::MXNetTPU::nd_copy_from_packed( $h, pack( 'f*', @$values ) );
    return $class->new_from_handle($h);
}

sub zeros {
    my ( $class, $shape ) = @_;
    my $n = 1;
    $n *= $_ for @$shape;
    return $class->array( [ (0) x $n ], $shape );
}

sub handle { $_[0]{handle} }

sub shape { [ AI::MXNetTPU::nd_shape( $_[0]{handle} ) ] }

sub size {
    my $n = 1;
    $n *= $_ for @{ $_[0]->shape };
    return $n;
}

sub aslist {
    my ($self) = @_;
    my $packed = AI::MXNetTPU::nd_copy_to_packed( $self->{handle},
        $self->size );
    return [ unpack( 'f*', $packed ) ];
}

sub copy_from {
    my ( $self, $other ) = @_;
    AI::MXNetTPU::nd_copy_from_nd( $self->{handle}, $other->handle );
    return $self;
}

sub set {
    my ( $self, $values ) = @_;
    AI::MXNetTPU::nd_copy_from_packed( $self->{handle},
        pack( 'f*', @$values ) );
    return $self;
}

# in-place SGD step through the registered optimizer op, exactly the
# reference Module update path (sgd_update kernel)
sub sgd_update {
    my ( $self, $grad, %opt ) = @_;
    my @keys = sort keys %opt;
    AI::MXNetTPU::imperative_invoke(
        'sgd_update',
        [ $self->{handle}, $grad->handle ],
        [ $self->{handle} ],
        \@keys, [ map { "" . $opt{$_} } @keys ]
    );
    return $self;
}

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::nd_free( $self->{handle} )
      if $self->{owned} && $self->{handle};
}

1;
