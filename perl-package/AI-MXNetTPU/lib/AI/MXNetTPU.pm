package AI::MXNetTPU;

# Perl binding for the mxnet_tpu framework.
#
# Reference counterpart: perl-package/AI-MXNet (the reference's full
# perl frontend). Same layering: this XS module is the AI-MXNetCAPI
# tier (raw MX* ABI), and the OO modules under AI::MXNetTPU::* are the
# AI::MXNet tier. Everything crosses through libmxtpu_c_api.so only —
# no Python in the consumer.

use strict;
use warnings;

our $VERSION = '0.01';

require XSLoader;
XSLoader::load( 'AI::MXNetTPU', $VERSION );

use AI::MXNetTPU::NDArray;
use AI::MXNetTPU::Symbol;
use AI::MXNetTPU::Executor;
use AI::MXNetTPU::IO;

sub nd  { 'AI::MXNetTPU::NDArray' }
sub sym { 'AI::MXNetTPU::Symbol' }

1;
__END__

=head1 NAME

AI::MXNetTPU - Perl interface to the mxnet_tpu deep learning framework

=head1 SYNOPSIS

    use AI::MXNetTPU;
    my $data  = AI::MXNetTPU::Symbol->variable('data');
    my $fc    = AI::MXNetTPU::Symbol->create('FullyConnected',
                    { num_hidden => 10 }, { data => $data }, 'fc');
    my $net   = AI::MXNetTPU::Symbol->create('SoftmaxOutput',
                    {}, { data => $fc }, 'softmax');
    my $exe   = AI::MXNetTPU::Executor->simple_bind($net,
                    { data => [ 32, 784 ], softmax_label => [32] });
    $exe->forward(1);
    $exe->backward;

=cut
