/* AI::MXNetTPU — Perl XS binding over the libmxtpu_c_api.so C ABI.
 *
 * Reference counterpart: perl-package/AI-MXNetCAPI (the SWIG-generated
 * layer under AI::MXNet, ~28k LoC perl surface). Same design: a thin
 * typemap layer over the MX* C functions; the OO surface lives in pure
 * perl (lib/AI/MXNetTPU/*.pm). Handles cross as IVs; tensors cross as
 * packed float32 strings (perl's native bulk-binary idiom).
 */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include "c_api.h"

#define MXCHECK(call) do { \
  if ((call) != 0) croak("mxnet_tpu: %s", MXGetLastError()); \
} while (0)

static void *iv_handle(pTHX_ SV *sv) {
  return INT2PTR(void *, SvIV(sv));
}

/* AV of SVs -> C handle array (caller frees) */
static void **av_handles(pTHX_ AV *av, int *n) {
  *n = av_len(av) + 1;
  void **out = (void **)malloc(sizeof(void *) * (*n > 0 ? *n : 1));
  int i;
  for (i = 0; i < *n; ++i) out[i] = iv_handle(aTHX_ *av_fetch(av, i, 0));
  return out;
}

static const char **av_strings(pTHX_ AV *av, int *n) {
  *n = av_len(av) + 1;
  const char **out =
      (const char **)malloc(sizeof(char *) * (*n > 0 ? *n : 1));
  int i;
  for (i = 0; i < *n; ++i) out[i] = SvPV_nolen(*av_fetch(av, i, 0));
  return out;
}

MODULE = AI::MXNetTPU  PACKAGE = AI::MXNetTPU  PREFIX = mxtpu_

PROTOTYPES: DISABLE

void
mxtpu_list_all_op_names()
  PPCODE:
    {
      mx_uint n = 0, i;
      const char **names = NULL;
      MXCHECK(MXListAllOpNames(&n, &names));
      EXTEND(SP, n);
      for (i = 0; i < n; ++i) PUSHs(sv_2mortal(newSVpv(names[i], 0)));
    }

IV
mxtpu_nd_create(shape_av, dtype_id)
    AV *shape_av
    int dtype_id
  CODE:
    {
      int n = av_len(shape_av) + 1, i;
      mx_uint shape[8];
      NDArrayHandle h = NULL;
      if (n > 8) croak("ndim > 8");
      for (i = 0; i < n; ++i)
        shape[i] = (mx_uint)SvIV(*av_fetch(shape_av, i, 0));
      MXCHECK(MXNDArrayCreateEx(shape, (mx_uint)n, 1, 0, 0, dtype_id, &h));
      RETVAL = PTR2IV(h);
    }
  OUTPUT:
    RETVAL

void
mxtpu_nd_free(h)
    IV h
  CODE:
    MXNDArrayFree(INT2PTR(void *, h));

void
mxtpu_nd_copy_from_packed(h, data_sv)
    IV h
    SV *data_sv
  CODE:
    {
      STRLEN len;
      const char *p = SvPV(data_sv, len);
      MXCHECK(MXNDArraySyncCopyFromCPU(INT2PTR(void *, h), p,
                                       len / sizeof(float)));
    }

SV *
mxtpu_nd_copy_to_packed(h, n_elem)
    IV h
    IV n_elem
  CODE:
    {
      if (n_elem <= 0) { RETVAL = newSVpvn("", 0); goto done; }
      SV *out = newSV(n_elem * sizeof(float));
      SvPOK_on(out);
      SvCUR_set(out, n_elem * sizeof(float));
      MXCHECK(MXNDArraySyncCopyToCPU(INT2PTR(void *, h), SvPVX(out),
                                     (size_t)n_elem));
      RETVAL = out;
      done: ;
    }
  OUTPUT:
    RETVAL

void
mxtpu_nd_shape(h)
    IV h
  PPCODE:
    {
      mx_uint ndim = 0, i;
      const mx_uint *dims = NULL;
      MXCHECK(MXNDArrayGetShape(INT2PTR(void *, h), &ndim, &dims));
      EXTEND(SP, ndim);
      for (i = 0; i < ndim; ++i) PUSHs(sv_2mortal(newSViv(dims[i])));
    }

void
mxtpu_nd_copy_from_nd(dst, src)
    IV dst
    IV src
  CODE:
    MXCHECK(MXNDArraySyncCopyFromNDArray(INT2PTR(void *, dst),
                                         INT2PTR(void *, src), -1));

void
mxtpu_imperative_invoke(op_name, ins_av, outs_av, keys_av, vals_av)
    const char *op_name
    AV *ins_av
    SV *outs_av
    AV *keys_av
    AV *vals_av
  PPCODE:
    {
      int n_in, n_keys, n_vals, i;
      int n_out = 0;
      NDArrayHandle *outs = NULL;
      NDArrayHandle fixed[16];
      /* output-count check precedes every allocation: croak longjmps */
      if (SvOK(outs_av) && SvROK(outs_av)
          && av_len((AV *)SvRV(outs_av)) + 1 > 16)
        croak("too many outputs");
      void **ins = av_handles(aTHX_ ins_av, &n_in);
      const char **keys = av_strings(aTHX_ keys_av, &n_keys);
      const char **vals = av_strings(aTHX_ vals_av, &n_vals);
      if (SvOK(outs_av) && SvROK(outs_av)) {
        AV *oav = (AV *)SvRV(outs_av);
        int no;
        void **oh = av_handles(aTHX_ oav, &no);
        for (i = 0; i < no; ++i) fixed[i] = oh[i];
        free(oh);
        n_out = no;
        outs = fixed;
      }
      int rc = MXImperativeInvoke(op_name, n_in, ins, &n_out, &outs,
                                  n_keys, keys, vals);
      free(ins); free(keys); free(vals);
      if (rc != 0) croak("mxnet_tpu: %s", MXGetLastError());
      EXTEND(SP, n_out);
      for (i = 0; i < n_out; ++i)
        PUSHs(sv_2mortal(newSViv(PTR2IV(outs[i]))));
    }

IV
mxtpu_sym_variable(name)
    const char *name
  CODE:
    {
      SymbolHandle h = NULL;
      MXCHECK(MXSymbolCreateVariable(name, &h));
      RETVAL = PTR2IV(h);
    }
  OUTPUT:
    RETVAL

IV
mxtpu_sym_create(op_name, keys_av, vals_av)
    const char *op_name
    AV *keys_av
    AV *vals_av
  CODE:
    {
      int nk, nv;
      const char **keys = av_strings(aTHX_ keys_av, &nk);
      const char **vals = av_strings(aTHX_ vals_av, &nv);
      SymbolHandle h = NULL;
      int rc = MXSymbolCreateAtomicSymbol(op_name, (mx_uint)nk, keys, vals,
                                          &h);
      free(keys); free(vals);
      if (rc != 0) croak("mxnet_tpu: %s", MXGetLastError());
      RETVAL = PTR2IV(h);
    }
  OUTPUT:
    RETVAL

void
mxtpu_sym_compose(h, name, keys_av, args_av)
    IV h
    const char *name
    AV *keys_av
    AV *args_av
  CODE:
    {
      int nk, na;
      const char **keys = av_strings(aTHX_ keys_av, &nk);
      void **args = av_handles(aTHX_ args_av, &na);
      int rc = MXSymbolCompose(INT2PTR(void *, h), name, (mx_uint)na,
                               nk ? keys : NULL, args);
      free(keys); free(args);
      if (rc != 0) croak("mxnet_tpu: %s", MXGetLastError());
    }

void
mxtpu_sym_list_arguments(h)
    IV h
  PPCODE:
    {
      mx_uint n = 0, i;
      const char **names = NULL;
      MXCHECK(MXSymbolListArguments(INT2PTR(void *, h), &n, &names));
      EXTEND(SP, n);
      for (i = 0; i < n; ++i) PUSHs(sv_2mortal(newSVpv(names[i], 0)));
    }

void
mxtpu_sym_list_aux(h)
    IV h
  PPCODE:
    {
      mx_uint n = 0, i;
      const char **names = NULL;
      MXCHECK(MXSymbolListAuxiliaryStates(INT2PTR(void *, h), &n, &names));
      EXTEND(SP, n);
      for (i = 0; i < n; ++i) PUSHs(sv_2mortal(newSVpv(names[i], 0)));
    }

SV *
mxtpu_sym_to_json(h)
    IV h
  CODE:
    {
      const char *json = NULL;
      MXCHECK(MXSymbolSaveToJSON(INT2PTR(void *, h), &json));
      RETVAL = newSVpv(json, 0);
    }
  OUTPUT:
    RETVAL

void
mxtpu_executor_simple_bind(sym, shape_names_av, shape_data_av, shape_idx_av)
    IV sym
    AV *shape_names_av
    AV *shape_data_av
    AV *shape_idx_av
  PPCODE:
    {
      int nn, i;
      const char **names = av_strings(aTHX_ shape_names_av, &nn);
      int nd = av_len(shape_data_av) + 1;
      int ni = av_len(shape_idx_av) + 1;
      mx_uint *data = (mx_uint *)malloc(sizeof(mx_uint) * (nd > 0 ? nd : 1));
      mx_uint *idx = (mx_uint *)malloc(sizeof(mx_uint) * (ni > 0 ? ni : 1));
      for (i = 0; i < nd; ++i)
        data[i] = (mx_uint)SvIV(*av_fetch(shape_data_av, i, 0));
      for (i = 0; i < ni; ++i)
        idx[i] = (mx_uint)SvIV(*av_fetch(shape_idx_av, i, 0));
      const char *req_types[] = {"write"};
      mx_uint num_in = 0, num_aux = 0;
      NDArrayHandle *in_args = NULL, *arg_grads = NULL, *aux = NULL;
      const char **upd_names = NULL;
      NDArrayHandle *upd_handles = NULL;
      int shared_len = 0;
      ExecutorHandle exe = NULL;
      int rc = MXExecutorSimpleBind(
          INT2PTR(void *, sym), 1, 0, 0, NULL, NULL, NULL, 0, NULL,
          req_types, (mx_uint)nn, names, data, idx, 0, NULL, NULL, 0, NULL,
          NULL, 0, NULL, &shared_len, NULL, NULL, &upd_names, &upd_handles,
          &num_in, &in_args, &arg_grads, &num_aux, &aux, NULL, &exe);
      free(names); free(data); free(idx);
      if (rc != 0) croak("mxnet_tpu: %s", MXGetLastError());
      /* returns (exe, \@in_args, \@arg_grads, \@aux) */
      {
        AV *a_in = newAV(), *a_gr = newAV(), *a_aux = newAV();
        mx_uint j;
        for (j = 0; j < num_in; ++j) {
          av_push(a_in, newSViv(PTR2IV(in_args[j])));
          av_push(a_gr, arg_grads[j] ? newSViv(PTR2IV(arg_grads[j]))
                                     : newSV(0));
        }
        for (j = 0; j < num_aux; ++j)
          av_push(a_aux, newSViv(PTR2IV(aux[j])));
        EXTEND(SP, 4);
        PUSHs(sv_2mortal(newSViv(PTR2IV(exe))));
        PUSHs(sv_2mortal(newRV_noinc((SV *)a_in)));
        PUSHs(sv_2mortal(newRV_noinc((SV *)a_gr)));
        PUSHs(sv_2mortal(newRV_noinc((SV *)a_aux)));
      }
    }

void
mxtpu_executor_forward(exe, is_train)
    IV exe
    int is_train
  CODE:
    MXCHECK(MXExecutorForward(INT2PTR(void *, exe), is_train));

void
mxtpu_executor_backward(exe)
    IV exe
  CODE:
    MXCHECK(MXExecutorBackward(INT2PTR(void *, exe), 0, NULL));

void
mxtpu_executor_outputs(exe)
    IV exe
  PPCODE:
    {
      mx_uint n = 0, i;
      NDArrayHandle *outs = NULL;
      MXCHECK(MXExecutorOutputs(INT2PTR(void *, exe), &n, &outs));
      EXTEND(SP, n);
      for (i = 0; i < n; ++i) PUSHs(sv_2mortal(newSViv(PTR2IV(outs[i]))));
    }

void
mxtpu_executor_free(exe)
    IV exe
  CODE:
    MXExecutorFree(INT2PTR(void *, exe));

IV
mxtpu_dataiter_create(iter_name, keys_av, vals_av)
    const char *iter_name
    AV *keys_av
    AV *vals_av
  CODE:
    {
      mx_uint n = 0, i;
      DataIterCreator *iters = NULL;
      DataIterCreator found = NULL;
      MXCHECK(MXListDataIters(&n, &iters));
      for (i = 0; i < n; ++i) {
        const char *nm, *desc;
        mx_uint na;
        const char **an, **at, **ad;
        MXCHECK(MXDataIterGetIterInfo(iters[i], &nm, &desc, &na, &an, &at,
                                      &ad));
        if (strcmp(nm, iter_name) == 0) { found = iters[i]; break; }
      }
      if (found == NULL) croak("mxnet_tpu: no data iter %s", iter_name);
      int nk, nv;
      const char **keys = av_strings(aTHX_ keys_av, &nk);
      const char **vals = av_strings(aTHX_ vals_av, &nv);
      DataIterHandle it = NULL;
      int rc = MXDataIterCreateIter(found, (mx_uint)nk, keys, vals, &it);
      free(keys); free(vals);
      if (rc != 0) croak("mxnet_tpu: %s", MXGetLastError());
      RETVAL = PTR2IV(it);
    }
  OUTPUT:
    RETVAL

void
mxtpu_dataiter_before_first(it)
    IV it
  CODE:
    MXCHECK(MXDataIterBeforeFirst(INT2PTR(void *, it)));

int
mxtpu_dataiter_next(it)
    IV it
  CODE:
    {
      int more = 0;
      MXCHECK(MXDataIterNext(INT2PTR(void *, it), &more));
      RETVAL = more;
    }
  OUTPUT:
    RETVAL

IV
mxtpu_dataiter_data(it)
    IV it
  CODE:
    {
      NDArrayHandle h = NULL;
      MXCHECK(MXDataIterGetData(INT2PTR(void *, it), &h));
      RETVAL = PTR2IV(h);
    }
  OUTPUT:
    RETVAL

IV
mxtpu_dataiter_label(it)
    IV it
  CODE:
    {
      NDArrayHandle h = NULL;
      MXCHECK(MXDataIterGetLabel(INT2PTR(void *, it), &h));
      RETVAL = PTR2IV(h);
    }
  OUTPUT:
    RETVAL

void
mxtpu_sym_free(h)
    IV h
  CODE:
    MXSymbolFree(INT2PTR(void *, h));

void
mxtpu_dataiter_free(it)
    IV it
  CODE:
    MXDataIterFree(INT2PTR(void *, it));

void
mxtpu_notify_shutdown()
  CODE:
    MXNotifyShutdown();
