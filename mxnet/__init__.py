"""``import mxnet as mx`` — drop-in alias for :mod:`mxnet_tpu`.

The reference's example/tool scripts all start with ``import mxnet as mx``
(e.g. example/image-classification/train_mnist.py:1); this package lets
them run unmodified against the TPU-native framework. Every attribute
resolves to the identical mxnet_tpu object, and submodules are registered
under both names in ``sys.modules`` so ``import mxnet.io`` and
``import mxnet_tpu.io`` yield the *same* module object (one op registry,
one engine — never a double import).
"""
import importlib
import sys

import mxnet_tpu as _base

_PKG = "mxnet_tpu"


def _register_aliases():
    for name, mod in list(sys.modules.items()):
        if name == _PKG or name.startswith(_PKG + "."):
            alias = "mxnet" + name[len(_PKG):]
            if alias != "mxnet":  # never clobber this alias package itself
                sys.modules.setdefault(alias, mod)


_register_aliases()

# Re-export the full top-level surface (classes, functions, submodule
# aliases like nd/sym/mod/init) by reference.
for _name in dir(_base):
    if not _name.startswith("__"):
        globals()[_name] = getattr(_base, _name)
__version__ = _base.__version__


def __getattr__(name):
    """Lazily resolve submodules not imported by mxnet_tpu/__init__."""
    try:
        mod = importlib.import_module(_PKG + "." + name)
    except ImportError as e:
        raise AttributeError("module 'mxnet' has no attribute %r" % name) from e
    sys.modules.setdefault("mxnet." + name, mod)
    _register_aliases()
    return mod
