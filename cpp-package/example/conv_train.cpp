/*
 * Train a small conv net from C++ using the GENERATED typed op
 * wrappers (op.h) — counterpart of the reference's
 * cpp-package/example/lenet.cpp built on its generated op.h.
 *
 * Build:
 *   g++ -std=c++17 conv_train.cpp -I.. -L../../mxnet_tpu/lib \
 *       -lmxtpu_c_api -Wl,-rpath,../../mxnet_tpu/lib -o conv_train
 */
#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

#include "include/mxnet-cpp/MxNetCpp.h"
#include "include/mxnet-cpp/op.h"

using namespace mxnet::cpp;

int main() {
  const int kBatch = 16, kEdge = 12, kClasses = 2;
  auto ctx = Context::cpu();

  /* conv -> relu -> pool -> flatten -> concat(flat, flat) -> fc -> softmax
   * (Concat exercises the var-input wrapper path) */
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("softmax_label");
  Symbol cw = Symbol::Variable("conv_weight");
  Symbol cb = Symbol::Variable("conv_bias");
  Symbol conv = op::Convolution("conv", data, cw, cb,
                                /*cudnn_off=*/false, "None", Shape(),
                                /*kernel=*/Shape(3, 3), "None",
                                /*no_bias=*/false, /*num_filter=*/4);
  Symbol act = op::Activation("relu1", conv, "relu");
  Symbol pool = op::Pooling("pool1", act, false, false, Shape(2, 2),
                            Shape(), "max", "valid", Shape(2, 2));
  Symbol flat = op::Flatten("flat", pool);
  Symbol cat = op::Concat("cat", {flat, flat}, 1);
  Symbol fw = Symbol::Variable("fc_weight");
  Symbol fb = Symbol::Variable("fc_bias");
  Symbol fc = op::FullyConnected("fc", cat, fw, fb, true, false, kClasses);
  Symbol net = op::SoftmaxOutput("softmax", fc, label);

  auto arg_names = net.ListArguments();
  auto arg_shapes = net.InferArgShapes(
      {{"data", {kBatch, 1, kEdge, kEdge}}, {"softmax_label", {kBatch}}});

  /* task: class = bright top half vs bright bottom half */
  std::mt19937 rng(3);
  std::uniform_real_distribution<float> uni(0.f, 0.3f);
  std::vector<float> xs(kBatch * kEdge * kEdge), ys(kBatch);
  for (int i = 0; i < kBatch; ++i) {
    int cls = i % kClasses;
    ys[i] = static_cast<float>(cls);
    for (int p = 0; p < kEdge * kEdge; ++p) {
      bool top = p < kEdge * kEdge / 2;
      xs[i * kEdge * kEdge + p] =
          uni(rng) + ((cls == 0) == top ? 0.8f : 0.0f);
    }
  }

  std::vector<NDArray> args, grads;
  std::vector<OpReqType> reqs;
  std::normal_distribution<float> norm(0.f, 0.1f);
  for (size_t i = 0; i < arg_names.size(); ++i) {
    NDArray a(arg_shapes[i], ctx);
    size_t sz = a.Size();
    std::vector<float> init(sz);
    if (arg_names[i] == "data") {
      init = xs;
    } else if (arg_names[i] == "softmax_label") {
      init = ys;
    } else {
      for (auto &v : init) v = norm(rng);
    }
    a.SyncCopyFromCPU(init.data(), sz);
    args.push_back(a);
    NDArray g(arg_shapes[i], ctx);
    std::vector<float> zeros(sz, 0.f);
    g.SyncCopyFromCPU(zeros.data(), sz);
    grads.push_back(g);
    bool is_param = arg_names[i] != "data" && arg_names[i] != "softmax_label";
    reqs.push_back(is_param ? kWriteTo : kNullOp);
  }

  Executor exe(net, ctx, args, grads, reqs, {});
  float acc = 0.f;
  for (int step = 0; step < 150; ++step) {
    exe.Forward(true);
    exe.Backward();
    for (size_t i = 0; i < args.size(); ++i) {
      if (reqs[i] != kWriteTo) continue;
      std::vector<NDArray> target{args[i]};
      Operator("sgd_update")
          .SetInput("weight", args[i])
          .SetInput("grad", grads[i])
          .SetParam("lr", 0.2f)
          .SetParam("rescale_grad", 1.0f / kBatch)
          .Invoke(&target);
    }
    if (step == 149) {
      auto outs = exe.outputs;
      auto probs = outs[0].CopyToVector();
      int correct = 0;
      for (int i = 0; i < kBatch; ++i) {
        int arg = 0;
        for (int c = 1; c < kClasses; ++c)
          if (probs[i * kClasses + c] > probs[i * kClasses + arg]) arg = c;
        if (arg == static_cast<int>(ys[i])) correct++;
      }
      acc = static_cast<float>(correct) / kBatch;
    }
  }
  if (acc < 0.95f) {
    std::fprintf(stderr, "accuracy %.3f too low\n", acc);
    return 1;
  }
  std::printf("CONV_TRAIN_OK acc=%.3f\n", acc);
  return 0;
}
