/*
 * Train a small MLP from C++ through the header-only API
 * (counterpart of the reference's cpp-package/example/mlp.cpp).
 *
 * Build:
 *   g++ -std=c++17 mlp_train.cpp -I.. -L../../mxnet_tpu/lib \
 *       -lmxtpu_c_api -Wl,-rpath,../../mxnet_tpu/lib -o mlp_train
 * Run with MXNET_TPU_HOME/PYTHONPATH pointing at the repo + site-packages.
 */
#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

#include "include/mxnet-cpp/MxNetCpp.h"

using namespace mxnet::cpp;

int main() {
  const int kBatch = 32, kFeat = 10, kHidden = 16, kClasses = 4;
  auto ctx = Context::cpu();

  /* net: data -> FC -> relu -> FC -> SoftmaxOutput */
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("softmax_label");
  Symbol fc1 = Operator("FullyConnected")
                   .SetParam("num_hidden", kHidden)
                   .SetInput("data", data)
                   .CreateSymbol("fc1");
  Symbol act = Operator("Activation")
                   .SetParam("act_type", "relu")
                   .SetInput("data", fc1)
                   .CreateSymbol("act1");
  Symbol fc2 = Operator("FullyConnected")
                   .SetParam("num_hidden", kClasses)
                   .SetInput("data", act)
                   .CreateSymbol("fc2");
  Symbol net = Operator("SoftmaxOutput")
                   .SetInput("data", fc2)
                   .SetInput("label", label)
                   .CreateSymbol("softmax");

  auto arg_shapes = net.InferArgShapes(
      {{"data", {kBatch, kFeat}}, {"softmax_label", {kBatch}}});
  auto arg_names = net.ListArguments();

  /* synthetic linearly separable task */
  std::mt19937 rng(7);
  std::normal_distribution<float> norm(0.f, 1.f);
  std::vector<float> xs(kBatch * kFeat), ys(kBatch);
  for (int i = 0; i < kBatch; ++i) {
    int cls = i % kClasses;
    ys[i] = static_cast<float>(cls);
    for (int j = 0; j < kFeat; ++j) {
      xs[i * kFeat + j] = norm(rng) * 0.3f + (j == cls ? 2.5f : 0.f);
    }
  }

  std::vector<NDArray> args, grads;
  std::vector<OpReqType> reqs;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    std::vector<float> init;
    size_t n = 1;
    for (mx_uint s : arg_shapes[i]) n *= s;
    init.resize(n);
    if (arg_names[i] == "data") {
      init = xs;
    } else if (arg_names[i] == "softmax_label") {
      init = ys;
    } else {
      for (auto &v : init) v = norm(rng) * 0.1f;
    }
    args.emplace_back(init, arg_shapes[i], ctx);
    grads.emplace_back(arg_shapes[i], ctx);
    bool is_input = arg_names[i] == "data" || arg_names[i] == "softmax_label";
    reqs.push_back(is_input ? kNullOp : kWriteTo);
  }

  Executor exe(net, ctx, args, grads, reqs);
  const float lr = 0.5f;
  float acc = 0.f;
  for (int step = 0; step < 60; ++step) {
    exe.Forward(true);
    exe.Backward();
    for (size_t i = 0; i < arg_names.size(); ++i) {
      if (reqs[i] != kWriteTo) continue;
      /* in-place sgd_update through the out= convention */
      Operator op("sgd_update");
      op.SetParam("lr", lr / kBatch);
      op.SetInput("weight", args[i]).SetInput("grad", grads[i]);
      std::vector<NDArray> outs = {args[i]};
      op.Invoke(&outs);
    }
    if (step == 59) {
      auto probs = exe.outputs[0].CopyToVector();
      int correct = 0;
      for (int i = 0; i < kBatch; ++i) {
        int best = 0;
        for (int c = 1; c < kClasses; ++c) {
          if (probs[i * kClasses + c] > probs[i * kClasses + best]) best = c;
        }
        correct += (best == static_cast<int>(ys[i]));
      }
      acc = static_cast<float>(correct) / kBatch;
    }
  }
  NDArray::WaitAll();
  std::printf("CPP_MLP_OK accuracy=%.3f\n", acc);
  return acc > 0.9f ? 0 : 1;
}
