/*
 * Header-only C++ API over the general C ABI.
 *
 * Reference counterpart: cpp-package/include/mxnet-cpp (8.5k LoC of
 * generated op wrappers + hand-written NDArray/Symbol/Executor/KVStore
 * classes over include/mxnet/c_api.h). Same idea, one header: RAII
 * wrappers, exceptions from MXGetLastError, an Operator builder that
 * reaches every registered op by name (the generated-wrapper surface
 * collapses to one dynamic builder, since the op registry is already
 * string-keyed end to end).
 *
 * Link against libmxtpu_c_api.so; see examples/predict and
 * tests/test_cpp_package.py for a full build line.
 */
#ifndef MXNET_CPP_MXNETCPP_H_
#define MXNET_CPP_MXNETCPP_H_

#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "../../../src/c_api.h"

namespace mxnet {
namespace cpp {

inline void Check(int rc) {
  if (rc != 0) {
    throw std::runtime_error(MXGetLastError());
  }
}

class Context {
 public:
  static Context cpu(int id = 0) { return Context(1, id); }
  static Context tpu(int id = 0) { return Context(2, id); }
  static Context gpu(int id = 0) { return Context(2, id); }  /* accel alias */
  int dev_type;
  int dev_id;

 private:
  Context(int type, int id) : dev_type(type), dev_id(id) {}
};

class NDArray {
 public:
  NDArray() : handle_(nullptr, &NDArray::Release) {}
  NDArray(const std::vector<mx_uint> &shape, const Context &ctx)
      : handle_(nullptr, &NDArray::Release) {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayCreate(shape.data(), static_cast<mx_uint>(shape.size()),
                          ctx.dev_type, ctx.dev_id, 0, 0, &h));
    handle_ = std::shared_ptr<void>(h, &Release);
  }
  NDArray(const std::vector<float> &data, const std::vector<mx_uint> &shape,
          const Context &ctx)
      : NDArray(shape, ctx) {
    SyncCopyFromCPU(data.data(), data.size());
  }
  explicit NDArray(NDArrayHandle owned)
      : handle_(owned, &NDArray::Release) {}

  NDArrayHandle GetHandle() const { return handle_.get(); }

  void SyncCopyFromCPU(const float *data, size_t size) {
    Check(MXNDArraySyncCopyFromCPU(handle_.get(), data, size));
  }
  void SyncCopyToCPU(float *data, size_t size) const {
    Check(MXNDArraySyncCopyToCPU(handle_.get(), data, size));
  }
  std::vector<mx_uint> GetShape() const {
    mx_uint dim;
    const mx_uint *pdata;
    Check(MXNDArrayGetShape(handle_.get(), &dim, &pdata));
    return std::vector<mx_uint>(pdata, pdata + dim);
  }
  size_t Size() const {
    size_t n = 1;
    for (mx_uint s : GetShape()) n *= s;
    return n;
  }
  std::vector<float> CopyToVector() const {
    std::vector<float> out(Size());
    SyncCopyToCPU(out.data(), out.size());
    return out;
  }
  static void WaitAll() { Check(MXNDArrayWaitAll()); }

 private:
  static void Release(NDArrayHandle h) {
    if (h != nullptr) MXNDArrayFree(h);
  }
  std::shared_ptr<void> handle_;
};

/* Tuple-valued op attribute, rendered "(a,b,...)" for the string-kwargs
 * C API (ref cpp-package Shape, shape.h). */
struct Shape {
  Shape() {}
  explicit Shape(std::vector<mx_uint> d) : dims(std::move(d)) {}
  Shape(mx_uint a) : dims{a} {}
  Shape(mx_uint a, mx_uint b) : dims{a, b} {}
  Shape(mx_uint a, mx_uint b, mx_uint c) : dims{a, b, c} {}
  Shape(mx_uint a, mx_uint b, mx_uint c, mx_uint d) : dims{a, b, c, d} {}
  std::string Str() const {
    std::string s = "(";
    for (size_t i = 0; i < dims.size(); ++i) {
      if (i) s += ",";
      s += std::to_string(dims[i]);
    }
    return s + ")";
  }
  std::vector<mx_uint> dims;
};

class Symbol {
 public:
  Symbol() : handle_(nullptr, &Symbol::Release) {}
  explicit Symbol(SymbolHandle owned) : handle_(owned, &Symbol::Release) {}

  bool IsNull() const { return handle_ == nullptr; }

  static Symbol Variable(const std::string &name) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }
  static Symbol FromJSON(const std::string &json) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h));
    return Symbol(h);
  }
  std::string ToJSON() const {
    const char *json = nullptr;
    Check(MXSymbolSaveToJSON(handle_.get(), &json));
    return json;
  }
  std::vector<std::string> ListArguments() const {
    return ListStrings(&MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return ListStrings(&MXSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return ListStrings(&MXSymbolListAuxiliaryStates);
  }
  /* Shape inference from named input shapes; returns arg shapes. */
  std::vector<std::vector<mx_uint>> InferArgShapes(
      const std::map<std::string, std::vector<mx_uint>> &input_shapes) const {
    std::vector<const char *> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> data;
    for (const auto &kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      for (mx_uint s : kv.second) data.push_back(s);
      indptr.push_back(static_cast<mx_uint>(data.size()));
    }
    mx_uint in_sz, out_sz, aux_sz;
    const mx_uint *in_nd, *out_nd, *aux_nd;
    const mx_uint **in_d, **out_d, **aux_d;
    int complete = 0;
    Check(MXSymbolInferShape(
        handle_.get(), static_cast<mx_uint>(keys.size()), keys.data(),
        indptr.data(), data.data(), &in_sz, &in_nd, &in_d, &out_sz, &out_nd,
        &out_d, &aux_sz, &aux_nd, &aux_d, &complete));
    if (!complete) throw std::runtime_error("InferShape incomplete");
    std::vector<std::vector<mx_uint>> shapes;
    for (mx_uint i = 0; i < in_sz; ++i) {
      shapes.emplace_back(in_d[i], in_d[i] + in_nd[i]);
    }
    return shapes;
  }
  SymbolHandle GetHandle() const { return handle_.get(); }

 private:
  template <typename F>
  std::vector<std::string> ListStrings(F fn) const {
    mx_uint size;
    const char **arr;
    Check(fn(handle_.get(), &size, &arr));
    std::vector<std::string> out;
    for (mx_uint i = 0; i < size; ++i) out.emplace_back(arr[i]);
    return out;
  }
  static void Release(SymbolHandle h) {
    if (h != nullptr) MXSymbolFree(h);
  }
  std::shared_ptr<void> handle_;
};

/* Dynamic op builder: Operator("FullyConnected")
 *    .SetParam("num_hidden", 4).SetInput("data", x).CreateSymbol("fc1")
 * — the cpp-package's generated per-op wrappers, collapsed to one class
 * (OpWrapperGenerator.py parity without codegen). */
class Operator {
 public:
  explicit Operator(const std::string &op_name) : op_name_(op_name) {}

  template <typename T>
  Operator &SetParam(const std::string &key, const T &value) {
    keys_.push_back(key);
    vals_.push_back(ToString(value));
    return *this;
  }
  Operator &SetInput(const std::string &name, const Symbol &sym) {
    input_keys_.push_back(name);
    input_syms_.push_back(sym);
    return *this;
  }
  Operator &SetInput(const std::string &name, const NDArray &arr) {
    nd_input_keys_.push_back(name);
    nd_inputs_.push_back(arr);
    return *this;
  }
  /* positional (unnamed) input — var-input ops like Concat */
  Operator &AddInput(const Symbol &sym) {
    unnamed_syms_.push_back(sym);
    return *this;
  }

  Symbol CreateSymbol(const std::string &name) {
    std::vector<const char *> ks, vs;
    for (auto &k : keys_) ks.push_back(k.c_str());
    for (auto &v : vals_) vs.push_back(v.c_str());
    SymbolHandle atom = nullptr;
    Check(MXSymbolCreateAtomicSymbol(op_name_.c_str(),
                                     static_cast<mx_uint>(ks.size()),
                                     ks.data(), vs.data(), &atom));
    std::vector<const char *> iks;
    std::vector<SymbolHandle> ias;
    for (size_t i = 0; i < input_syms_.size(); ++i) {
      iks.push_back(input_keys_[i].c_str());
      ias.push_back(input_syms_[i].GetHandle());
    }
    if (!unnamed_syms_.empty() && !input_syms_.empty()) {
      /* positional compose would silently drop the names and rebind
       * everything in insertion order — refuse instead */
      throw std::runtime_error(
          "Operator: cannot mix SetInput(name, sym) with AddInput(sym)");
    }
    for (const auto &s : unnamed_syms_) ias.push_back(s.GetHandle());
    /* all-positional composition passes null keys (backend *args) */
    const char **keys_arg =
        unnamed_syms_.empty() ? iks.data() : nullptr;
    Check(MXSymbolCompose(atom, name.c_str(),
                          static_cast<mx_uint>(ias.size()), keys_arg,
                          ias.data()));
    return Symbol(atom);
  }

  /* imperative form: run the op on NDArray inputs right now */
  std::vector<NDArray> Invoke() {
    int num_out = 0;
    NDArrayHandle *outs = nullptr;
    DoInvoke(&num_out, &outs);
    std::vector<NDArray> result;
    for (int i = 0; i < num_out; ++i) result.emplace_back(outs[i]);
    return result;
  }

  /* out= form: write results into existing arrays (in-place ops like
   * sgd_update mutate their target without any host round-trip) */
  void Invoke(std::vector<NDArray> *outputs) {
    std::vector<NDArrayHandle> oh;
    for (auto &a : *outputs) oh.push_back(a.GetHandle());
    int num_out = static_cast<int>(oh.size());
    NDArrayHandle *op_ptr = oh.data();
    DoInvoke(&num_out, &op_ptr);
  }

 private:
  void DoInvoke(int *num_out, NDArrayHandle **outs) {
    std::vector<const char *> ks, vs;
    for (auto &k : keys_) ks.push_back(k.c_str());
    for (auto &v : vals_) vs.push_back(v.c_str());
    std::vector<NDArrayHandle> ins;
    for (auto &a : nd_inputs_) ins.push_back(a.GetHandle());
    Check(MXImperativeInvoke(op_name_.c_str(),
                             static_cast<int>(ins.size()), ins.data(),
                             num_out, outs,
                             static_cast<int>(ks.size()), ks.data(),
                             vs.data()));
  }

  template <typename T>
  static std::string ToString(const T &v) {
    return std::to_string(v);
  }
  static std::string ToString(const std::string &v) { return v; }
  static std::string ToString(const char *v) { return v; }
  static std::string ToString(const Shape &v) { return v.Str(); }
  static std::string ToString(bool v) { return v ? "true" : "false"; }

  std::string op_name_;
  std::vector<std::string> keys_, vals_;
  std::vector<std::string> input_keys_;
  std::vector<Symbol> input_syms_;
  std::vector<Symbol> unnamed_syms_;
  std::vector<std::string> nd_input_keys_;
  std::vector<NDArray> nd_inputs_;
};

enum OpReqType { kNullOp = 0, kWriteTo = 1, kAddTo = 3 };

class Executor {
 public:
  Executor(const Symbol &sym, const Context &ctx,
           const std::vector<NDArray> &args,
           const std::vector<NDArray> &arg_grads,
           const std::vector<OpReqType> &grad_reqs,
           const std::vector<NDArray> &aux = {})
      : handle_(nullptr, &Executor::Release), args_(args),
        arg_grads_(arg_grads) {
    std::vector<NDArrayHandle> a, g;
    std::vector<mx_uint> r;
    for (auto &x : args) a.push_back(x.GetHandle());
    for (auto &x : arg_grads) g.push_back(x.GetHandle());
    for (auto q : grad_reqs) r.push_back(static_cast<mx_uint>(q));
    std::vector<NDArrayHandle> ax;
    for (auto &x : aux) ax.push_back(x.GetHandle());
    ExecutorHandle h = nullptr;
    Check(MXExecutorBind(sym.GetHandle(), ctx.dev_type, ctx.dev_id,
                         static_cast<mx_uint>(a.size()), a.data(), g.data(),
                         r.data(), static_cast<mx_uint>(ax.size()),
                         ax.data(), &h));
    handle_ = std::shared_ptr<void>(h, &Release);
  }

  void Forward(bool is_train) {
    Check(MXExecutorForward(handle_.get(), is_train ? 1 : 0));
    RefreshOutputs();
  }
  void Backward(const std::vector<NDArray> &head_grads = {}) {
    std::vector<NDArrayHandle> hg;
    for (auto &x : head_grads) hg.push_back(x.GetHandle());
    Check(MXExecutorBackward(handle_.get(),
                             static_cast<mx_uint>(hg.size()),
                             hg.empty() ? nullptr : hg.data()));
  }
  std::vector<NDArray> outputs;
  const std::vector<NDArray> &arg_arrays() const { return args_; }
  const std::vector<NDArray> &grad_arrays() const { return arg_grads_; }

 private:
  void RefreshOutputs() {
    mx_uint n;
    NDArrayHandle *outs;
    Check(MXExecutorOutputs(handle_.get(), &n, &outs));
    outputs.clear();
    for (mx_uint i = 0; i < n; ++i) outputs.emplace_back(outs[i]);
  }
  static void Release(ExecutorHandle h) {
    if (h != nullptr) MXExecutorFree(h);
  }
  std::shared_ptr<void> handle_;
  std::vector<NDArray> args_, arg_grads_;
};

class KVStore {
 public:
  explicit KVStore(const std::string &type = "local")
      : handle_(nullptr, &KVStore::Release) {
    KVStoreHandle h = nullptr;
    Check(MXKVStoreCreate(type.c_str(), &h));
    handle_ = std::shared_ptr<void>(h, &Release);
  }
  void Init(const std::string &key, const NDArray &val) {
    const char *k = key.c_str();
    NDArrayHandle v = val.GetHandle();
    Check(MXKVStoreInitEx(handle_.get(), 1, &k, &v));
  }
  void Push(const std::string &key, const NDArray &val, int priority = 0) {
    const char *k = key.c_str();
    NDArrayHandle v = val.GetHandle();
    Check(MXKVStorePushEx(handle_.get(), 1, &k, &v, priority));
  }
  void Pull(const std::string &key, NDArray *out, int priority = 0) {
    const char *k = key.c_str();
    NDArrayHandle v = out->GetHandle();
    Check(MXKVStorePullEx(handle_.get(), 1, &k, &v, priority));
  }
  int GetRank() const {
    int r;
    Check(MXKVStoreGetRank(handle_.get(), &r));
    return r;
  }
  int GetNumWorkers() const {
    int n;
    Check(MXKVStoreGetGroupSize(handle_.get(), &n));
    return n;
  }
  void Barrier() { Check(MXKVStoreBarrier(handle_.get())); }

 private:
  static void Release(KVStoreHandle h) {
    if (h != nullptr) MXKVStoreFree(h);
  }
  std::shared_ptr<void> handle_;
};

}  // namespace cpp
}  // namespace mxnet

#endif  /* MXNET_CPP_MXNETCPP_H_ */
