#' Internal plumbing: load libmxtpu_c_api.so and call its .C-convention
#' R shim tier (src/c_api_r.cc).
#'
#' Reference counterpart: R-package/src Rcpp glue — redesigned here as a
#' pure-R binding so no compilation happens at install time: handles are
#' 8-byte raw vectors, numeric data crosses as double (the shim casts to
#' float32), and string results arrive in preallocated buffers.

.MXNetEnv <- new.env()

mx.internal.lib.path <- function() {
  p <- Sys.getenv("MXTPU_CAPI_LIB", "")
  if (nzchar(p)) return(p)
  # common layouts: repo checkout (env MXTPU_ROOT) or alongside package
  root <- Sys.getenv("MXTPU_ROOT", "")
  if (nzchar(root)) {
    cand <- file.path(root, "mxnet_tpu", "lib", "libmxtpu_c_api.so")
    if (file.exists(cand)) return(cand)
  }
  stop(paste("cannot locate libmxtpu_c_api.so;",
             "set MXTPU_CAPI_LIB or MXTPU_ROOT"))
}

mx.internal.load <- function() {
  if (!is.null(.MXNetEnv$dll)) return(invisible(NULL))
  .MXNetEnv$dll <- dyn.load(mx.internal.lib.path(), local = FALSE)
  invisible(NULL)
}

mx.internal.last.error <- function() {
  buf <- paste(rep(" ", 4096), collapse = "")
  r <- .C("MXRGetLastError", out = buf, len = as.integer(4096),
          rc = as.integer(0))
  trimws(r$out)
}

#' Call a shim function; stop() with the backend message on failure.
#' Every shim function's last argument is rc (int, 0 = ok). NAOK: NaN/Inf
#' are legitimate tensor values and must round-trip (reference parity).
mx.internal.C <- function(fname, ...) {
  mx.internal.load()
  res <- .C(fname, ..., rc = as.integer(0), NAOK = TRUE)
  if (res$rc != 0) {
    stop(sprintf("%s: %s", fname, mx.internal.last.error()))
  }
  res
}

mx.internal.new.handle <- function() raw(8)

mx.internal.null.handle <- function(h) all(h == as.raw(0))

#' Pack a list of handles (raw(8) each) into one raw vector.
mx.internal.pack.handles <- function(handles) {
  if (length(handles) == 0) return(raw(0))
  do.call(c, handles)
}

mx.internal.unpack.handles <- function(buf, n) {
  lapply(seq_len(n), function(i) buf[(8 * (i - 1) + 1):(8 * i)])
}

#' A blank string buffer for shim string returns.
mx.internal.strbuf <- function(n = 65536) paste(rep(" ", n), collapse = "")

mx.internal.split.lines <- function(s) {
  s <- trimws(s, which = "right")
  if (!nzchar(s)) return(character(0))
  strsplit(s, "\n", fixed = TRUE)[[1]]
}

#' Framework version (MXGetVersion through the shim).
#' @export
mx.version <- function() {
  r <- mx.internal.C("MXRGetVersion", out = as.integer(0))
  r$out
}

#' Seed the framework RNG (reference parity: mx.set.seed).
#' @export
mx.set.seed <- function(seed) {
  invisible(mx.internal.C("MXRRandomSeed", seed = as.integer(seed)))
}

#' Block until all pending device work completes.
#' @export
mx.nd.waitall <- function() {
  invisible(mx.internal.C("MXRNDArrayWaitAll"))
}

#' All registered operator names.
#' @export
mx.internal.op.names <- function() {
  buf <- mx.internal.strbuf()
  r <- mx.internal.C("MXRListAllOpNames", buf = buf,
                     len = as.integer(nchar(buf)))
  mx.internal.split.lines(r$buf)
}
