#' Executor: bind a Symbol and run it (reference parity:
#' R-package/R/executor.R over MXExecutorSimpleBind).

#' Bind a symbol with named input shapes; the framework allocates
#' argument, gradient and auxiliary arrays.
#'
#' @param symbol the network
#' @param ctx device context
#' @param grad.req "write", "add" or "null"
#' @param ... named R-convention shapes (data = c(784, 64), ...)
#' @return an MXExecutor: arg.arrays / grad.arrays / aux.arrays are
#'   named lists of NDArrays (names follow mx.symbol.arguments order)
#' @export
mx.simple.bind <- function(symbol, ctx = NULL, grad.req = "write", ...) {
  if (is.null(ctx)) ctx <- mx.ctx.default()
  provided <- list(...)
  keys <- names(provided)
  cshapes <- lapply(provided, function(s) rev(as.integer(s)))
  ind <- c(0L, cumsum(vapply(cshapes, length, 1L)))
  flat <- as.integer(unlist(cshapes))
  if (length(flat) == 0) flat <- integer(0)
  arg_cap <- 4096L
  aux_cap <- 4096L
  r <- mx.internal.C("MXRExecutorSimpleBind", sym = symbol$handle,
                     dev_type = ctx$device_typeid, dev_id = ctx$device_id,
                     n_provided = length(provided), keys = keys,
                     ind_ptr = ind, shape_data = flat,
                     grad_req = grad.req,
                     arg_cap = arg_cap, in_args = raw(8 * arg_cap),
                     arg_grads = raw(8 * arg_cap), n_args = as.integer(0),
                     aux_cap = aux_cap, aux_states = raw(8 * aux_cap),
                     n_aux = as.integer(0),
                     out = mx.internal.new.handle())
  exec <- new.env(parent = emptyenv())
  exec$handle <- r$out
  arg_names <- mx.symbol.arguments(symbol)
  aux_names <- mx.symbol.auxiliary.states(symbol)
  wrap_all <- function(buf, n, nms) {
    hs <- mx.internal.unpack.handles(buf, n)
    out <- vector("list", n)   # out[i] <- list(NULL) keeps the slot;
    for (i in seq_len(n)) {    # out[[i]] <- NULL would delete it
      if (!mx.internal.null.handle(hs[[i]])) {
        out[[i]] <- mx.internal.nd.wrap(hs[[i]])
      }
    }
    names(out) <- nms[seq_len(n)]
    out
  }
  exec$arg.arrays <- wrap_all(r$in_args, r$n_args, arg_names)
  exec$grad.arrays <- wrap_all(r$arg_grads, r$n_args, arg_names)
  exec$aux.arrays <- wrap_all(r$aux_states, r$n_aux, aux_names)
  exec$symbol <- symbol
  class(exec) <- "MXExecutor"
  reg.finalizer(exec, function(e) {
    if (!is.null(e$handle) && !mx.internal.null.handle(e$handle)) {
      tryCatch(.C("MXRExecutorFree", exec = e$handle, rc = as.integer(0)),
               error = function(err) NULL)
      e$handle <- NULL
    }
  })
  exec
}

#' Run the forward pass.
#' @export
mx.exec.forward <- function(exec, is.train = TRUE) {
  mx.internal.C("MXRExecutorForward", exec = exec$handle,
                is_train = as.integer(is.train))
  invisible(exec)
}

#' Run the backward pass (loss heads supply their own head grads,
#' reference parity: Executor::Backward with ones).
#' @export
mx.exec.backward <- function(exec) {
  mx.internal.C("MXRExecutorBackward", exec = exec$handle)
  invisible(exec)
}

#' Fetch output NDArrays.
#' @export
mx.exec.outputs <- function(exec) {
  cap <- 64L
  r <- mx.internal.C("MXRExecutorOutputs", exec = exec$handle, cap = cap,
                     out_handles = raw(8 * cap), n = as.integer(0))
  out <- lapply(mx.internal.unpack.handles(r$out_handles, r$n),
                mx.internal.nd.wrap)
  names(out) <- mx.symbol.outputs(exec$symbol)[seq_len(r$n)]
  out
}

#' Copy host values into bound argument arrays by name.
#' @export
mx.exec.update.arg.arrays <- function(exec, arg.arrays) {
  for (nm in names(arg.arrays)) {
    dst <- exec$arg.arrays[[nm]]
    if (is.null(dst)) next
    v <- arg.arrays[[nm]]
    if (is.mx.ndarray(v)) v <- as.array(v)
    mx.nd.internal.copyfrom(dst, v)
  }
  invisible(exec)
}
