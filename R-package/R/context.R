#' Device contexts (reference parity: R-package/R/context.R).
#'
#' On the TPU-native stack both mx.cpu() and mx.gpu() resolve to the
#' framework's device table — mx.gpu maps to the TPU tier the same way
#' the python frontend's mx.gpu does (mxnet_tpu/context.py).

mx.internal.ctx <- function(dev_type, dev_id) {
  structure(list(device = dev_type, device_id = dev_id,
                 device_typeid = if (dev_type == "cpu") 1L else 2L),
            class = "MXContext")
}

#' @export
mx.cpu <- function(dev.id = 0) mx.internal.ctx("cpu", as.integer(dev.id))

#' @export
mx.gpu <- function(dev.id = 0) mx.internal.ctx("gpu", as.integer(dev.id))

#' @export
mx.tpu <- function(dev.id = 0) mx.internal.ctx("gpu", as.integer(dev.id))

#' @export
is.mx.context <- function(x) inherits(x, "MXContext")

#' Default context (settable, reference parity: mx.ctx.default).
#' @export
mx.ctx.default <- function(new = NULL) {
  if (!is.null(new)) .MXNetEnv$ctx <- new
  if (is.null(.MXNetEnv$ctx)) .MXNetEnv$ctx <- mx.cpu()
  .MXNetEnv$ctx
}
