#' FeedForward model tier (reference parity: R-package/R/model.R
#' mx.model.FeedForward.create / predict / save / load).
#'
#' The training loop drives the executor tier directly: simple-bind,
#' copy params in, forward/backward per batch, sgd(-momentum) update via
#' the imperative optimizer ops (src/operator/optimizer_op parity ops
#' sgd_update / sgd_mom_update) — the same loop the reference model.R
#' runs, minus the multi-device split (the TPU stack scales through the
#' fused SPMD step on the python tier instead).

mx.internal.train.batch <- function(exec, optim_state, trainable,
                                    learning.rate, momentum, wd,
                                    clip_gradient) {
  mx.exec.forward(exec, is.train = TRUE)
  mx.exec.backward(exec)
  for (nm in trainable) {
    w <- exec$arg.arrays[[nm]]
    g <- exec$grad.arrays[[nm]]
    if (is.null(w) || is.null(g)) next
    params <- list(lr = learning.rate, wd = wd)
    if (!is.null(clip_gradient)) params$clip_gradient <- clip_gradient
    if (momentum > 0) {
      params$momentum <- momentum
      mx.nd.internal.invoke("sgd_mom_update",
                            list(w, g, optim_state[[nm]]),
                            params, out = list(w))
    } else {
      mx.nd.internal.invoke("sgd_update", list(w, g), params,
                            out = list(w))
    }
  }
  invisible(NULL)
}

#' Train a FeedForward model from a data iterator.
#'
#' @param symbol network with a loss head (e.g. SoftmaxOutput)
#' @param X an MXDataIter
#' @param ctx device context
#' @param num.round epochs
#' @param learning.rate,momentum,wd,clip_gradient SGD hyper-parameters
#' @param initializer weight initializer factory (mx.init.*)
#' @param eval.metric an mx.metric (train metric, printed per epoch)
#' @param batch.end.callback function(epoch, nbatch, metric_value)
#' @param verbose print per-epoch metric
#' @return mx.model list(symbol, arg.params, aux.params)
#' @export
mx.model.FeedForward.create <- function(symbol, X, ctx = NULL,
                                        num.round = 10,
                                        learning.rate = 0.01,
                                        momentum = 0, wd = 0,
                                        clip_gradient = NULL,
                                        initializer = mx.init.uniform(0.01),
                                        eval.metric = mx.metric.accuracy,
                                        batch.end.callback = NULL,
                                        data.name = "data",
                                        label.name = NULL,
                                        verbose = TRUE) {
  if (is.null(ctx)) ctx <- mx.ctx.default()
  arg_names <- mx.symbol.arguments(symbol)
  if (is.null(label.name)) {
    label.name <- grep("label", arg_names, value = TRUE)[1]
  }
  mx.io.iter.reset(X)
  stopifnot(mx.io.iter.next(X))
  dshape <- dim(mx.io.iter.data(X))
  lshape <- dim(mx.io.iter.label(X))
  input.shapes <- list(dshape, lshape)
  names(input.shapes) <- c(data.name, label.name)

  init <- mx.internal.init.params(symbol, input.shapes, initializer, ctx)
  bind_args <- c(list(symbol, ctx = ctx, grad.req = "write"), input.shapes)
  exec <- do.call(mx.simple.bind, bind_args)
  mx.exec.update.arg.arrays(exec, init$arg.params)
  for (nm in names(init$aux.params)) {
    if (!is.null(exec$aux.arrays[[nm]])) {
      mx.nd.internal.copyfrom(exec$aux.arrays[[nm]],
                              as.array(init$aux.params[[nm]]))
    }
  }
  trainable <- setdiff(arg_names, c(data.name, label.name))
  optim_state <- list()
  for (nm in trainable) {
    if (!is.null(exec$arg.arrays[[nm]])) {
      optim_state[[nm]] <- mx.nd.zeros(dim(exec$arg.arrays[[nm]]), ctx)
    }
  }

  for (epoch in seq_len(num.round)) {
    mx.io.iter.reset(X)
    state <- eval.metric$init()
    nbatch <- 0
    while (mx.io.iter.next(X)) {
      mx.nd.internal.copyfrom(exec$arg.arrays[[data.name]],
                              as.array(mx.io.iter.data(X)))
      label <- mx.io.iter.label(X)
      mx.nd.internal.copyfrom(exec$arg.arrays[[label.name]],
                              as.array(label))
      mx.internal.train.batch(exec, optim_state, trainable,
                              learning.rate, momentum, wd, clip_gradient)
      out <- mx.exec.outputs(exec)[[1]]
      state <- eval.metric$update(label, out, state)
      nbatch <- nbatch + 1
      if (!is.null(batch.end.callback)) {
        batch.end.callback(epoch, nbatch, eval.metric$get(state))
      }
    }
    if (verbose) {
      cat(sprintf("Epoch [%d] Train-%s=%f\n", epoch, eval.metric$name,
                  eval.metric$get(state)))
    }
  }

  arg.params <- list()
  for (nm in trainable) {
    if (!is.null(exec$arg.arrays[[nm]])) {
      arg.params[[nm]] <- mx.nd.array(as.array(exec$arg.arrays[[nm]]), ctx)
    }
  }
  aux.params <- list()
  for (nm in names(exec$aux.arrays)) {
    aux.params[[nm]] <- mx.nd.array(as.array(exec$aux.arrays[[nm]]), ctx)
  }
  structure(list(symbol = symbol, arg.params = arg.params,
                 aux.params = aux.params, data.name = data.name,
                 label.name = label.name),
            class = "MXFeedForwardModel")
}

#' Predict over an iterator; returns the concatenated output matrix in
#' R layout (classes, n).
#' @export
predict.MXFeedForwardModel <- function(object, X, ctx = NULL, ...) {
  if (is.null(ctx)) ctx <- mx.ctx.default()
  mx.io.iter.reset(X)
  stopifnot(mx.io.iter.next(X))
  dshape <- dim(mx.io.iter.data(X))
  lshape <- dim(mx.io.iter.label(X))
  input.shapes <- list(dshape, lshape)
  names(input.shapes) <- c(object$data.name, object$label.name)
  bind_args <- c(list(object$symbol, ctx = ctx, grad.req = "null"),
                 input.shapes)
  exec <- do.call(mx.simple.bind, bind_args)
  mx.exec.update.arg.arrays(exec, object$arg.params)
  for (nm in names(object$aux.params)) {
    if (!is.null(exec$aux.arrays[[nm]])) {
      mx.nd.internal.copyfrom(exec$aux.arrays[[nm]],
                              as.array(object$aux.params[[nm]]))
    }
  }
  mx.io.iter.reset(X)
  chunks <- list()
  while (mx.io.iter.next(X)) {
    mx.nd.internal.copyfrom(exec$arg.arrays[[object$data.name]],
                            as.array(mx.io.iter.data(X)))
    mx.exec.forward(exec, is.train = FALSE)
    pad <- mx.io.iter.padnum(X)
    out <- as.array(mx.exec.outputs(exec)[[1]])
    keep <- ncol(out) - pad
    chunks[[length(chunks) + 1]] <- out[, seq_len(keep), drop = FALSE]
  }
  do.call(cbind, chunks)
}

#' Save a model's params + symbol in the framework's checkpoint format
#' (interoperates with python mx.model.load_checkpoint).
#' @export
mx.model.save <- function(model, prefix, iteration) {
  mx.symbol.save(model$symbol, sprintf("%s-symbol.json", prefix))
  packed <- list()
  for (nm in names(model$arg.params)) {
    packed[[paste0("arg:", nm)]] <- model$arg.params[[nm]]
  }
  for (nm in names(model$aux.params)) {
    packed[[paste0("aux:", nm)]] <- model$aux.params[[nm]]
  }
  mx.nd.save(packed, sprintf("%s-%04d.params", prefix, iteration))
  invisible(NULL)
}

#' Load a checkpoint saved by any frontend.
#' @export
mx.model.load <- function(prefix, iteration) {
  symbol <- mx.symbol.load(sprintf("%s-symbol.json", prefix))
  packed <- mx.nd.load(sprintf("%s-%04d.params", prefix, iteration))
  arg.params <- list()
  aux.params <- list()
  for (nm in names(packed)) {
    if (startsWith(nm, "arg:")) {
      arg.params[[substring(nm, 5)]] <- packed[[nm]]
    } else if (startsWith(nm, "aux:")) {
      aux.params[[substring(nm, 5)]] <- packed[[nm]]
    }
  }
  structure(list(symbol = symbol, arg.params = arg.params,
                 aux.params = aux.params, data.name = "data",
                 label.name = grep("label",
                                   mx.symbol.arguments(symbol),
                                   value = TRUE)[1]),
            class = "MXFeedForwardModel")
}
