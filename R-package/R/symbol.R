#' Symbol: the declarative graph tier (reference parity:
#' R-package/R/symbol.R). Symbols compose through the C ABI
#' (MXSymbolCreateAtomicSymbol + MXSymbolCompose), so a graph built in R
#' is byte-identical JSON to one built from python or perl.

mx.internal.sym.wrap <- function(handle) {
  s <- new.env(parent = emptyenv())
  s$handle <- handle
  class(s) <- "MXSymbol"
  reg.finalizer(s, function(e) {
    if (!is.null(e$handle) && !mx.internal.null.handle(e$handle)) {
      tryCatch(.C("MXRSymbolFree", sym = e$handle, rc = as.integer(0)),
               error = function(err) NULL)
      e$handle <- NULL
    }
  })
  s
}

#' @export
is.mx.symbol <- function(x) inherits(x, "MXSymbol")

#' Create a variable (placeholder) symbol.
#' @export
mx.symbol.Variable <- function(name) {
  r <- mx.internal.C("MXRSymbolCreateVariable", name = name,
                     out = mx.internal.new.handle())
  mx.internal.sym.wrap(r$out)
}

#' Create + compose an operator symbol.
#'
#' @param op registered operator name
#' @param args mixed list: MXSymbol entries become graph inputs
#'   (keyword-composed when named), scalars become op attributes;
#'   a `name` entry names the node.
#' @export
mx.internal.symbol.create <- function(op, args) {
  nm <- ""
  sym_args <- list()
  params <- list()
  arg_names <- names(args)
  if (is.null(arg_names)) arg_names <- rep("", length(args))
  for (i in seq_along(args)) {
    v <- args[[i]]
    k <- arg_names[i]
    if (identical(k, "name")) {
      nm <- as.character(v)
    } else if (is.mx.symbol(v)) {
      sym_args[[length(sym_args) + 1]] <- v
      names(sym_args)[length(sym_args)] <- k
    } else if (is.list(v) && length(v) > 0 && is.mx.symbol(v[[1]])) {
      for (s in v) {
        sym_args[[length(sym_args) + 1]] <- s
        names(sym_args)[length(sym_args)] <- ""
      }
    } else if (!is.null(v)) {
      params[[k]] <- v
    }
  }
  keys <- as.character(names(params))
  vals <- vapply(params, function(v) {
    if (is.logical(v)) (if (v) "1" else "0")
    else if (is.numeric(v) && length(v) > 1)
      paste0("(", paste(v, collapse = ","), ")")
    else as.character(v)
  }, "")
  if (length(keys) == 0) { keys <- ""; vals <- "" }
  r <- mx.internal.C("MXRSymbolCreateAtomic", op = op,
                     n_kv = length(params), keys = keys, vals = vals,
                     out = mx.internal.new.handle())
  sym <- mx.internal.sym.wrap(r$out)
  if (length(sym_args) > 0) {
    snames <- names(sym_args)
    has_keys <- as.integer(!is.null(snames) && all(nzchar(snames)))
    if (has_keys == 0L) snames <- rep("", length(sym_args))
    mx.internal.C("MXRSymbolCompose", sym = sym$handle, name = nm,
                  n_args = length(sym_args), has_keys = has_keys,
                  keys = snames,
                  args = mx.internal.pack.handles(
                    lapply(sym_args, function(s) s$handle)))
  }
  sym
}

mx.internal.symbol.list <- function(sym, which) {
  buf <- mx.internal.strbuf()
  r <- mx.internal.C("MXRSymbolList", sym = sym$handle,
                     which = as.integer(which), buf = buf,
                     len = as.integer(nchar(buf)))
  mx.internal.split.lines(r$buf)
}

#' @export
mx.symbol.arguments <- function(sym) mx.internal.symbol.list(sym, 0)

#' @export
mx.symbol.outputs <- function(sym) mx.internal.symbol.list(sym, 1)

#' @export
mx.symbol.auxiliary.states <- function(sym) mx.internal.symbol.list(sym, 2)

#' Graph JSON (interoperates with python/perl save/load).
#' @export
mx.symbol.tojson <- function(sym) {
  buf <- mx.internal.strbuf(1048576)
  r <- mx.internal.C("MXRSymbolSaveToJSON", sym = sym$handle, buf = buf,
                     len = as.integer(nchar(buf)))
  trimws(r$buf)
}

#' @export
mx.symbol.load.json <- function(json) {
  r <- mx.internal.C("MXRSymbolCreateFromJSON", json = json,
                     out = mx.internal.new.handle())
  mx.internal.sym.wrap(r$out)
}

#' @export
mx.symbol.save <- function(sym, filename) {
  writeLines(mx.symbol.tojson(sym), path.expand(filename))
  invisible(NULL)
}

#' @export
mx.symbol.load <- function(filename) {
  mx.symbol.load.json(paste(readLines(path.expand(filename)),
                            collapse = "\n"))
}

#' Infer shapes from named input shapes (R-convention shapes in,
#' R-convention shapes out).
#'
#' @param sym the symbol
#' @param ... named shapes, e.g. data = c(784, 64)
#' @return list(arg.shapes=, out.shapes=, aux.shapes=) named lists, or
#'   NULL when inference is incomplete
#' @export
mx.symbol.infer.shape <- function(sym, ...) {
  provided <- list(...)
  keys <- names(provided)
  cshapes <- lapply(provided, function(s) rev(as.integer(s)))
  ind <- c(0L, cumsum(vapply(cshapes, length, 1L)))
  flat <- as.integer(unlist(cshapes))
  if (length(flat) == 0) flat <- integer(0)
  grab <- function(which, nms) {
    cap <- 65536L
    ndims_cap <- 8192L
    r <- mx.internal.C("MXRSymbolInferShape", sym = sym$handle,
                       n_provided = length(provided), keys = keys,
                       ind_ptr = ind, shape_data = flat,
                       which = as.integer(which), out_n = as.integer(0),
                       out_ndims = integer(ndims_cap),
                       ndims_cap = ndims_cap, out_shapes = integer(cap),
                       shape_cap = cap, complete = as.integer(0))
    if (r$complete == 0) return(NULL)
    shapes <- list()
    off <- 0
    for (i in seq_len(r$out_n)) {
      d <- r$out_ndims[i]
      shapes[[i]] <- rev(r$out_shapes[(off + 1):(off + d)])
      off <- off + d
    }
    names(shapes) <- nms
    shapes
  }
  args <- grab(0, mx.symbol.arguments(sym))
  if (is.null(args)) return(NULL)
  list(arg.shapes = args,
       out.shapes = grab(1, mx.symbol.outputs(sym)),
       aux.shapes = grab(2, mx.symbol.auxiliary.states(sym)))
}

#' @export
print.MXSymbol <- function(x, ...) {
  cat(sprintf("<MXSymbol outputs=%s>\n",
              paste(mx.symbol.outputs(x), collapse = ", ")))
  invisible(x)
}
