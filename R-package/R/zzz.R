#' mxnet.tpu: R frontend for the TPU-native MXNet framework.
#'
#' Pure-R binding over libmxtpu_c_api.so (src/c_api_r.cc shim tier).
#' Reference parity surface: R-package/R (ndarray, symbol, executor,
#' model, io) re-designed without install-time compilation.
#'
#' @docType package
#' @name mxnet.tpu
NULL

.onLoad <- function(libname, pkgname) {
  # lazy: the shared library loads on first use so the package can be
  # attached (e.g. for docs) on machines without the framework built
  invisible(NULL)
}

.onUnload <- function(libpath) {
  if (!is.null(.MXNetEnv$dll)) {
    tryCatch(dyn.unload(.MXNetEnv$dll[["path"]]),
             error = function(e) NULL)
    .MXNetEnv$dll <- NULL
  }
}
