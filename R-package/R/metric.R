#' Evaluation metrics (reference parity: R-package/R/metric.R).

mx.internal.metric <- function(name, init, update, get) {
  structure(list(name = name, init = init, update = update, get = get),
            class = "mx.metric")
}

#' Classification accuracy. Predictions follow the R layout:
#' (classes, batch); labels are 0-based class ids.
#' @export
mx.metric.accuracy <- mx.internal.metric(
  "accuracy",
  init = function() c(0, 0),
  update = function(label, pred, state) {
    pa <- as.array(pred)
    la <- as.array(label)
    hit <- sum((max.col(t(pa)) - 1) == as.integer(la))
    state + c(hit, length(la))
  },
  get = function(state) state[1] / max(state[2], 1)
)

#' Mean squared error.
#' @export
mx.metric.mse <- mx.internal.metric(
  "mse",
  init = function() c(0, 0),
  update = function(label, pred, state) {
    pa <- as.array(pred)
    la <- as.array(label)
    state + c(sum((pa - la)^2), length(la))
  },
  get = function(state) state[1] / max(state[2], 1)
)
