#' Data iterators over the C ABI DataIter surface (reference parity:
#' R-package/R/io.R; the creators are the same registry python's
#' mx.io uses, so MNISTIter/ImageRecordIter/CSVIter behave identically).

mx.internal.iter.wrap <- function(handle) {
  it <- new.env(parent = emptyenv())
  it$handle <- handle
  class(it) <- "MXDataIter"
  reg.finalizer(it, function(e) {
    if (!is.null(e$handle) && !mx.internal.null.handle(e$handle)) {
      tryCatch(.C("MXRDataIterFree", iter = e$handle, rc = as.integer(0)),
               error = function(err) NULL)
      e$handle <- NULL
    }
  })
  it
}

#' Names of the registered data iterators.
#' @export
mx.io.list.iters <- function() {
  buf <- mx.internal.strbuf()
  r <- mx.internal.C("MXRListDataIters", buf = buf,
                     len = as.integer(nchar(buf)))
  mx.internal.split.lines(r$buf)
}

#' Create a named iterator with string-typed kwargs.
#' @export
mx.io.internal.create <- function(name, ...) {
  params <- list(...)
  keys <- as.character(names(params))
  vals <- vapply(params, function(v) {
    if (is.logical(v)) (if (v) "True" else "False")
    else if (is.numeric(v) && length(v) > 1)
      paste0("(", paste(v, collapse = ","), ")")
    else as.character(v)
  }, "")
  if (length(keys) == 0) { keys <- ""; vals <- "" }
  r <- mx.internal.C("MXRDataIterCreate", name = name,
                     n_kv = length(params), keys = keys, vals = vals,
                     out = mx.internal.new.handle())
  mx.internal.iter.wrap(r$out)
}

#' MNIST iterator (reference parity: mx.io.MNISTIter).
#' @export
mx.io.MNISTIter <- function(...) mx.io.internal.create("MNISTIter", ...)

#' CSV iterator.
#' @export
mx.io.CSVIter <- function(...) mx.io.internal.create("CSVIter", ...)

#' ImageRecord iterator.
#' @export
mx.io.ImageRecordIter <- function(...) {
  mx.io.internal.create("ImageRecordIter", ...)
}

#' Advance; FALSE at end of epoch.
#' @export
mx.io.iter.next <- function(iter) {
  r <- mx.internal.C("MXRDataIterNext", iter = iter$handle,
                     out = as.integer(0))
  r$out != 0
}

#' Rewind to the epoch start.
#' @export
mx.io.iter.reset <- function(iter) {
  mx.internal.C("MXRDataIterBeforeFirst", iter = iter$handle)
  invisible(iter)
}

#' Current batch data (NDArray).
#' @export
mx.io.iter.data <- function(iter) {
  r <- mx.internal.C("MXRDataIterGetData", iter = iter$handle,
                     out = mx.internal.new.handle())
  mx.internal.nd.wrap(r$out)
}

#' Current batch label (NDArray).
#' @export
mx.io.iter.label <- function(iter) {
  r <- mx.internal.C("MXRDataIterGetLabel", iter = iter$handle,
                     out = mx.internal.new.handle())
  mx.internal.nd.wrap(r$out)
}

#' Pad rows in the current (tail) batch.
#' @export
mx.io.iter.padnum <- function(iter) {
  r <- mx.internal.C("MXRDataIterGetPadNum", iter = iter$handle,
                     pad = as.integer(0))
  r$pad
}
