#' NDArray: device tensors (reference parity: R-package/R/ndarray.R).
#'
#' Layout convention matches the reference R package: R arrays are
#' column-major, the backend is row-major, so shapes are REVERSED at the
#' boundary and the flat data is passed through unchanged — an R array
#' of dim c(784, 64) becomes a backend (64, 784) tensor. as.array()
#' round-trips exactly. The .C tier is float32 (the reference R surface
#' is single-precision too).

mx.internal.nd.wrap <- function(handle) {
  nd <- new.env(parent = emptyenv())
  nd$handle <- handle
  class(nd) <- "MXNDArray"
  reg.finalizer(nd, function(e) {
    if (!is.null(e$handle) && !mx.internal.null.handle(e$handle)) {
      tryCatch(.C("MXRNDArrayFree", handle = e$handle, rc = as.integer(0)),
               error = function(err) NULL)
      e$handle <- NULL
    }
  })
  nd
}

#' @export
is.mx.ndarray <- function(x) inherits(x, "MXNDArray")

#' Create an empty NDArray of the given R-convention shape.
#' @export
mx.nd.internal.empty <- function(shape, ctx = NULL) {
  if (is.null(ctx)) ctx <- mx.ctx.default()
  if (!is.mx.context(ctx)) stop("ctx must come from mx.cpu()/mx.gpu()")
  cshape <- rev(as.integer(shape))   # R column-major -> backend row-major
  r <- mx.internal.C("MXRNDArrayCreate", shape = cshape,
                     ndim = length(cshape),
                     dev_type = ctx$device_typeid,
                     dev_id = ctx$device_id,
                     out = mx.internal.new.handle())
  mx.internal.nd.wrap(r$out)
}

#' Create an NDArray from an R vector/matrix/array.
#' @export
mx.nd.array <- function(src.array, ctx = NULL) {
  if (is.mx.ndarray(src.array)) return(src.array)
  shape <- dim(src.array)
  if (is.null(shape)) shape <- length(src.array)
  nd <- mx.nd.internal.empty(shape, ctx)
  data <- as.double(src.array)
  mx.internal.C("MXRNDArraySyncCopyFromDouble", handle = nd$handle,
                data = data, n = length(data))
  nd
}

#' @export
dim.MXNDArray <- function(x) {
  r <- mx.internal.C("MXRNDArrayGetShape", handle = x$handle,
                     ndim = as.integer(16), shape = integer(16))
  rev(r$shape[seq_len(r$ndim)])
}

#' @export
length.MXNDArray <- function(x) prod(dim(x))

#' @export
as.array.MXNDArray <- function(x, ...) {
  shape <- dim(x)
  n <- prod(shape)
  r <- mx.internal.C("MXRNDArraySyncCopyToDouble", handle = x$handle,
                     out = double(n), n = as.integer(n))
  array(r$out, dim = shape)
}

#' @export
print.MXNDArray <- function(x, ...) {
  cat(sprintf("<MXNDArray %s>\n", paste(dim(x), collapse = "x")))
  print(as.array(x))
}

#' Invoke a registered operator imperatively.
#'
#' @param op operator name ("FullyConnected", "sgd_update", ...)
#' @param ndargs list of MXNDArray inputs (order matters)
#' @param params named list of scalar attributes
#' @param out NULL (allocate outputs) or a list of MXNDArrays to write
#' @return a single MXNDArray, or a list when the op has several outputs
#' @export
mx.nd.internal.invoke <- function(op, ndargs, params = list(), out = NULL) {
  in_buf <- mx.internal.pack.handles(lapply(ndargs, function(a) a$handle))
  keys <- as.character(names(params))
  vals <- vapply(params, function(v) {
    if (is.logical(v)) (if (v) "1" else "0")
    else if (is.numeric(v) && length(v) > 1)
      paste0("(", paste(v, collapse = ","), ")")
    else as.character(v)
  }, "")
  if (length(keys) == 0) { keys <- ""; vals <- "" }
  cap <- 16L
  if (is.null(out)) {
    r <- mx.internal.C("MXRImperativeInvoke", op = op,
                       n_in = length(ndargs), in_handles = in_buf,
                       n_out = as.integer(0), out_cap = cap,
                       out_handles = raw(8 * cap),
                       n_kv = length(params), keys = keys, vals = vals)
    hs <- mx.internal.unpack.handles(r$out_handles, r$n_out)
    res <- lapply(hs, mx.internal.nd.wrap)
    if (length(res) == 1) res[[1]] else res
  } else {
    out_buf <- mx.internal.pack.handles(lapply(out, function(a) a$handle))
    mx.internal.C("MXRImperativeInvoke", op = op,
                  n_in = length(ndargs), in_handles = in_buf,
                  n_out = length(out), out_cap = cap,
                  out_handles = out_buf,
                  n_kv = length(params), keys = keys, vals = vals)
    if (length(out) == 1) out[[1]] else out
  }
}

#' @export
mx.nd.zeros <- function(shape, ctx = NULL) {
  nd <- mx.nd.internal.empty(shape, ctx)
  data <- double(prod(shape))
  mx.internal.C("MXRNDArraySyncCopyFromDouble", handle = nd$handle,
                data = data, n = length(data))
  nd
}

#' @export
mx.nd.ones <- function(shape, ctx = NULL) {
  nd <- mx.nd.internal.empty(shape, ctx)
  data <- rep(1.0, prod(shape))
  mx.internal.C("MXRNDArraySyncCopyFromDouble", handle = nd$handle,
                data = data, n = length(data))
  nd
}

#' Copy host data into an existing NDArray (shapes must agree).
#' @export
mx.nd.internal.copyfrom <- function(nd, src.array) {
  data <- as.double(src.array)
  mx.internal.C("MXRNDArraySyncCopyFromDouble", handle = nd$handle,
                data = data, n = length(data))
  nd
}

#' Arithmetic: scalars ride the *_scalar ops (no host round-trip);
#' tensor-tensor uses the elemwise ops; other R vectors are lifted,
#' erroring on length mismatch rather than silently recycling.
mx.internal.nd.binop <- function(op, scalar_op, rscalar_op, e1, e2) {
  lift <- function(v, like) {
    if (is.mx.ndarray(v)) return(v)
    if (length(v) != length(like)) {
      stop(sprintf("length mismatch: %d vs %d", length(v), length(like)))
    }
    mx.nd.array(array(as.double(v), dim = dim(like)))
  }
  if (is.mx.ndarray(e1) && is.mx.ndarray(e2)) {
    mx.nd.internal.invoke(op, list(e1, e2))
  } else if (is.mx.ndarray(e1) && is.numeric(e2) && length(e2) == 1) {
    mx.nd.internal.invoke(scalar_op, list(e1), list(scalar = e2))
  } else if (is.mx.ndarray(e2) && is.numeric(e1) && length(e1) == 1) {
    mx.nd.internal.invoke(rscalar_op, list(e2), list(scalar = e1))
  } else if (is.mx.ndarray(e1)) {
    mx.nd.internal.invoke(op, list(e1, lift(e2, e1)))
  } else {
    mx.nd.internal.invoke(op, list(lift(e1, e2), e2))
  }
}

#' @export
"+.MXNDArray" <- function(e1, e2) {
  mx.internal.nd.binop("elemwise_add", "_plus_scalar", "_plus_scalar",
                       e1, e2)
}

#' @export
"-.MXNDArray" <- function(e1, e2) {
  mx.internal.nd.binop("elemwise_sub", "_minus_scalar", "_rminus_scalar",
                       e1, e2)
}

#' @export
"*.MXNDArray" <- function(e1, e2) {
  mx.internal.nd.binop("elemwise_mul", "_mul_scalar", "_mul_scalar",
                       e1, e2)
}

#' @export
"/.MXNDArray" <- function(e1, e2) {
  mx.internal.nd.binop("elemwise_div", "_div_scalar", "_rdiv_scalar",
                       e1, e2)
}

#' Save a (named) list of NDArrays (reference parity: mx.nd.save).
#' @export
mx.nd.save <- function(ndarray, filename) {
  if (!is.list(ndarray)) ndarray <- list(ndarray)
  keys <- names(ndarray)
  has_keys <- as.integer(!is.null(keys) && all(nzchar(keys)))
  if (has_keys == 0L) keys <- rep("", length(ndarray))
  mx.internal.C("MXRNDArraySave", fname = path.expand(filename),
                n = length(ndarray),
                handles = mx.internal.pack.handles(
                  lapply(ndarray, function(a) a$handle)),
                has_keys = has_keys, keys = keys)
  invisible(NULL)
}

#' Load NDArrays saved by any frontend of the framework.
#' @export
mx.nd.load <- function(filename) {
  cap <- 4096L
  names_buf <- mx.internal.strbuf()
  r <- mx.internal.C("MXRNDArrayLoad", fname = path.expand(filename),
                     cap = cap, handles = raw(8 * cap),
                     n_out = as.integer(0), names_buf = names_buf,
                     names_len = as.integer(nchar(names_buf)))
  hs <- mx.internal.unpack.handles(r$handles, r$n_out)
  out <- lapply(hs, mx.internal.nd.wrap)
  nms <- mx.internal.split.lines(r$names_buf)
  if (length(nms) == length(out)) names(out) <- nms
  out
}
