#' Initializers (reference parity: R-package/R/initializer.R).

#' Uniform initializer factory.
#' @export
mx.init.uniform <- function(scale = 0.01) {
  function(name, shape) {
    array(runif(prod(shape), -scale, scale), dim = shape)
  }
}

#' Normal initializer factory.
#' @export
mx.init.normal <- function(sd = 0.01) {
  function(name, shape) {
    array(rnorm(prod(shape), 0, sd), dim = shape)
  }
}

#' Xavier initializer factory (reference parity: mx.init.Xavier;
#' fan computation mirrors initializer.py Xavier with R-reversed dims —
#' the backend row-major shape is rev(shape)).
#' @export
mx.init.Xavier <- function(rnd_type = "uniform", factor_type = "avg",
                           magnitude = 3) {
  function(name, shape) {
    cshape <- rev(shape)   # backend convention: (out, in, ...)
    hw <- if (length(cshape) > 2) prod(cshape[3:length(cshape)]) else 1
    fan_out <- cshape[1] * hw
    fan_in <- if (length(cshape) > 1) cshape[2] * hw else fan_out
    factor <- switch(factor_type, avg = (fan_in + fan_out) / 2,
                     `in` = fan_in, out = fan_out)
    scale <- sqrt(magnitude / factor)
    if (rnd_type == "uniform") {
      array(runif(prod(shape), -scale, scale), dim = shape)
    } else {
      array(rnorm(prod(shape), 0, scale), dim = shape)
    }
  }
}

#' Apply an initializer over inferred argument shapes. Bias/beta start
#' at zero, gamma/moving variance at one (reference parity:
#' mx.model.init.params).
#' @export
mx.internal.init.params <- function(symbol, input.shapes, initializer,
                                    ctx = NULL) {
  inferred <- do.call(mx.symbol.infer.shape, c(list(symbol), input.shapes))
  if (is.null(inferred)) stop("shape inference incomplete")
  arg_params <- list()
  for (nm in names(inferred$arg.shapes)) {
    if (nm %in% names(input.shapes)) next
    shape <- inferred$arg.shapes[[nm]]
    host <- if (grepl("(bias|beta)$", nm)) {
      array(0, dim = shape)
    } else if (grepl("gamma$", nm)) {
      array(1, dim = shape)
    } else {
      initializer(nm, shape)
    }
    arg_params[[nm]] <- mx.nd.array(host, ctx)
  }
  aux_params <- list()
  for (nm in names(inferred$aux.shapes)) {
    shape <- inferred$aux.shapes[[nm]]
    host <- if (grepl("var$", nm)) array(1, dim = shape)
            else array(0, dim = shape)
    aux_params[[nm]] <- mx.nd.array(host, ctx)
  }
  list(arg.params = arg_params, aux.params = aux_params)
}
