# MNIST MLP in pure R through libmxtpu_c_api.so (.C shim tier).
#
# Reference counterpart: R-package/vignettes mnist flow
# (mx.model.FeedForward.create on MNISTIter). Run via Rscript with:
#   MXTPU_CAPI_LIB=<path to libmxtpu_c_api.so>
#   MXTPU_R_PKG=<path to R-package>
#   Rscript train_mnist.R <train-images> <train-labels>
# Prints R_MNIST_OK on success (train accuracy >= 0.95 and checkpoint
# roundtrip byte-stable predictions).

args <- commandArgs(trailingOnly = TRUE)
if (length(args) < 2) stop("usage: train_mnist.R <images> <labels>")

pkg <- Sys.getenv("MXTPU_R_PKG", "")
if (!nzchar(pkg)) stop("set MXTPU_R_PKG to the R-package directory")
for (f in c("base.R", "context.R", "ndarray.R", "symbol.R", "executor.R",
            "io.R", "initializer.R", "metric.R", "model.R",
            "ops.generated.R")) {
  source(file.path(pkg, "R", f))
}

set.seed(42)

train <- mx.io.MNISTIter(image = args[1], label = args[2],
                         batch_size = 64, flat = "True",
                         shuffle = "False")

data <- mx.symbol.Variable("data")
fc1 <- mx.symbol.FullyConnected(data, name = "fc1", num_hidden = 64)
act1 <- mx.symbol.Activation(fc1, name = "relu1", act_type = "relu")
fc2 <- mx.symbol.FullyConnected(act1, name = "fc2", num_hidden = 10)
net <- mx.symbol.SoftmaxOutput(fc2, name = "softmax")

stopifnot(identical(
  mx.symbol.arguments(net),
  c("data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
    "softmax_label")))

model <- mx.model.FeedForward.create(
  net, train, num.round = 12, learning.rate = 0.2, momentum = 0.9,
  initializer = mx.init.Xavier(), eval.metric = mx.metric.accuracy)

# train accuracy via predict (shuffle is off, so label order is stable)
pred <- predict(model, train)
mx.io.iter.reset(train)
labels <- c()
while (mx.io.iter.next(train)) {
  pad <- mx.io.iter.padnum(train)
  la <- as.array(mx.io.iter.label(train))
  labels <- c(labels, la[seq_len(length(la) - pad)])
}
acc <- mean((max.col(t(pred)) - 1) == as.integer(labels))
cat(sprintf("final train accuracy: %f\n", acc))
stopifnot(acc >= 0.95)

# checkpoint roundtrip: predictions must be identical after save/load
prefix <- file.path(tempdir(), "r_mnist")
mx.model.save(model, prefix, 12)
model2 <- mx.model.load(prefix, 12)
pred2 <- predict(model2, train)
stopifnot(max(abs(pred - pred2)) < 1e-6)

cat("R_MNIST_OK\n")
