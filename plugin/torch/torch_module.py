"""Torch bridge plugin: run torch functions as framework operators.

Reference counterpart: ``plugin/torch`` — the reference embeds (lua)
Torch modules and criteria as mxnet operators (torch_module-inl.h),
letting users graft kernels from the other framework into a graph.
Same capability against today's torch: ``TorchOp`` wraps any
``torch.nn.functional`` (or ``torch.*``) function as a Custom op —
forward runs the torch kernel on host tensors, backward flows through
``torch.autograd`` — so it composes with the executor, autograd, and
Module like any native operator.

Usage::

    import plugin.torch.torch_module  # registers op_type='torch_op'
    y = mx.sym.Custom(x, op_type="torch_op", fn="relu")
    z = mx.sym.Custom(a, b, op_type="torch_op", fn="mul", num_args=2)
"""
from __future__ import annotations

import numpy as np

import mxnet_tpu as mx


def _resolve(fn_name):
    import torch
    import torch.nn.functional as F

    if hasattr(F, fn_name):
        return getattr(F, fn_name)
    if hasattr(torch, fn_name):
        return getattr(torch, fn_name)
    raise mx.MXNetError(
        "torch plugin: %r not found in torch.nn.functional or torch"
        % fn_name)


class TorchOp(mx.operator.CustomOp):
    def __init__(self, fn, n_in, kwargs):
        self._fn = fn
        self._n_in = n_in
        self._kwargs = kwargs
        self._saved = None

    def forward(self, is_train, req, in_data, out_data, aux):
        import torch

        if not is_train:
            # inference: no autograd graph, no residuals pinned
            with torch.no_grad():
                out = self._fn(*[torch.tensor(x.asnumpy())
                                 for x in in_data], **self._kwargs)
            self._saved = None
            self.assign(out_data[0], req[0], mx.nd.array(out.numpy()))
            return
        tins = [torch.tensor(x.asnumpy(), requires_grad=True)
                for x in in_data]
        out = self._fn(*tins, **self._kwargs)
        self._saved = (tins, out)
        self.assign(out_data[0], req[0],
                    mx.nd.array(out.detach().numpy()))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        import torch

        tins, out = self._saved
        grads = torch.autograd.grad(
            out, tins, torch.tensor(out_grad[0].asnumpy()),
            allow_unused=True)
        for i, g in enumerate(grads):
            if g is None:
                self.assign(in_grad[i], req[i],
                            mx.nd.zeros(in_data[i].shape))
            else:
                self.assign(in_grad[i], req[i], mx.nd.array(g.numpy()))


@mx.operator.register("torch_op")
class TorchOpProp(mx.operator.CustomOpProp):
    def __init__(self, fn="relu", num_args="1", **kwargs):
        super().__init__(need_top_grad=True)
        self._fn_name = str(fn)
        self._n_in = int(num_args)
        # remaining kwargs forward to the torch callable, parsed from
        # their string form (the Custom-op attr convention)
        self._kwargs = {}
        for k, v in kwargs.items():
            if v in ("True", "False", "None"):   # bool/None survive the
                self._kwargs[k] = {"True": True, "False": False,
                                   "None": None}[v]   # attr stringification
                continue
            try:
                self._kwargs[k] = int(v)
            except ValueError:
                try:
                    self._kwargs[k] = float(v)
                except ValueError:
                    self._kwargs[k] = v

    def list_arguments(self):
        return ["data%d" % i for i in range(self._n_in)]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        import torch

        fn = _resolve(self._fn_name)
        outs = fn(*[torch.zeros(tuple(s)) for s in in_shape],
                  **self._kwargs)
        if not torch.is_tensor(outs):
            raise mx.MXNetError(
                "torch plugin: %r returns %s — only single-tensor-output "
                "functions can be wrapped as torch_op"
                % (self._fn_name, type(outs).__name__))
        return in_shape, [list(outs.shape)], []

    def create_operator(self, ctx, in_shapes, in_dtypes=None):
        return TorchOp(_resolve(self._fn_name), self._n_in, self._kwargs)
