"""OpenCV plugin: cv2-backed image ops + an augmenting ImageIter.

Reference counterpart: plugin/opencv/ (opencv.py + cv_api.cc) — there
the decode/resize/border kernels are C++ OpenCV behind the C API; here
cv2's own native kernels fill that role and results land directly in
framework NDArrays. The ImageIter mirrors the reference class: file
list in, decode -> augment (resize / rand_crop / rand_mirror) ->
NCHW float batches out, drop-in as a Module.fit data source.

Import requires cv2 (pip opencv); everything else is framework-only.
"""
import random

import cv2
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io as mxio
from mxnet_tpu.ndarray import ndarray as nd


def imdecode(str_img, flag=1):
    """Decode a compressed image buffer into an HWC BGR NDArray
    (ref plugin/opencv/opencv.py imdecode)."""
    buf = np.frombuffer(
        str_img if isinstance(str_img, (bytes, bytearray))
        else str_img.encode("latin1"), np.uint8)
    img = cv2.imdecode(buf, flag)
    if img is None:
        raise ValueError("imdecode: buffer is not a valid image")
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(img.astype(np.float32))

def resize(src, size, interpolation=cv2.INTER_LINEAR):
    """Resize an HWC NDArray/array to ``size`` = (w, h)."""
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = cv2.resize(img, tuple(size), interpolation=interpolation)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out.astype(np.float32))


def copyMakeBorder(src, top, bot, left, right,
                   border_type=cv2.BORDER_CONSTANT, value=0):
    """Pad an HWC NDArray/array (ref cv_api.cc MXCVcopyMakeBorder)."""
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = cv2.copyMakeBorder(img, top, bot, left, right, border_type,
                             value=value)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out.astype(np.float32))


def scale_down(src_size, size):
    """Scale size down to fit src_size, preserving aspect (ref helper)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def fixed_crop(src, x0, y0, w, h, size=None):
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = img[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != tuple(size):
        out = cv2.resize(out, tuple(size))
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out.astype(np.float32))


def random_crop(src, size):
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h, size)


class ImageIter(mxio.DataIter):
    """Augmenting image iterator over (path, label) lists.

    Ref: plugin/opencv/opencv.py ImageListIter. Each epoch: optional
    shuffle; per image decode -> resize shorter side -> random or
    center crop to ``data_shape`` -> optional mirror -> NCHW float.
    """

    def __init__(self, img_list, data_shape, batch_size, resize_size=None,
                 rand_crop=False, rand_mirror=False, shuffle=False,
                 mean=None, data_name="data", label_name="softmax_label"):
        super(ImageIter, self).__init__(batch_size)
        if len(data_shape) != 3 or data_shape[0] not in (1, 3):
            raise ValueError("data_shape must be (C, H, W)")
        self.img_list = list(img_list)
        self.data_shape = tuple(data_shape)
        self.resize_size = resize_size
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.shuffle = shuffle
        self.mean = mean
        self.data_name = data_name
        self.label_name = label_name
        self._order = list(range(len(self.img_list)))
        self.reset()

    @property
    def provide_data(self):
        return [(self.data_name, (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [(self.label_name, (self.batch_size,))]

    def reset(self):
        self.cursor = 0
        if self.shuffle:
            random.shuffle(self._order)

    def _load_one(self, path):
        flag = 1 if self.data_shape[0] == 3 else 0
        img = cv2.imread(path, flag)
        if img is None:
            raise IOError("ImageIter: cannot read %r" % path)
        if img.ndim == 2:
            img = img[:, :, None]
        if self.resize_size is not None:
            short = min(img.shape[:2])
            scale = float(self.resize_size) / short
            nw = max(int(img.shape[1] * scale + 0.5), self.data_shape[2])
            nh = max(int(img.shape[0] * scale + 0.5), self.data_shape[1])
            img = cv2.resize(img, (nw, nh))
            if img.ndim == 2:
                img = img[:, :, None]
        c, th, tw = self.data_shape
        h, w = img.shape[:2]
        if h < th or w < tw:
            raise ValueError(
                "ImageIter: image %dx%d smaller than data_shape %dx%d "
                "(set resize_size to upscale)" % (h, w, th, tw))
        if self.rand_crop:
            x0 = random.randint(0, w - tw)
            y0 = random.randint(0, h - th)
        else:
            x0, y0 = (w - tw) // 2, (h - th) // 2
        img = img[y0:y0 + th, x0:x0 + tw]
        if self.rand_mirror and random.random() < 0.5:
            img = img[:, ::-1]
        out = img.astype(np.float32).transpose(2, 0, 1)   # HWC -> CHW
        if self.mean is not None:
            out -= self.mean
        return out

    def next(self):
        if self.cursor >= len(self.img_list):
            raise StopIteration
        n = self.batch_size
        data = np.zeros((n,) + self.data_shape, np.float32)
        label = np.zeros((n,), np.float32)
        pad = 0
        for i in range(n):
            if self.cursor < len(self.img_list):
                path, lab = self.img_list[self._order[self.cursor]]
                data[i] = self._load_one(path)
                label[i] = lab
                self.cursor += 1
            else:
                pad += 1
        return mxio.DataBatch(data=[nd.array(data)],
                              label=[nd.array(label)], pad=pad,
                              provide_data=self.provide_data,
                              provide_label=self.provide_label)
