"""Caffe layer execution bridge: run a caffe::Layer as a framework op.

Reference counterpart: plugin/caffe/caffe_op.cc — embeds a live caffe
layer inside an mxnet operator (forward/backward delegate to the caffe
blobs). Here the same contract rides the CustomOp host bridge: the op
instantiates a layer through pycaffe and moves tensors across the host
boundary at this node.

Honesty note: pycaffe is NOT present in this image; importing
``CaffeOpProp`` works (so graphs can be built and serialized), but
executing it raises a clear error unless a ``caffe`` module providing
``layers_dict()``-style construction is importable. The test suite
proves the bridge mechanics with a stub caffe implementing the same
surface (tests/test_caffe_converter.py), exactly how the reference CI
gates its caffe plugin on a caffe build.

    net = mx.sym.Custom(data=data, op_type="CaffePluginOp",
                        prototxt="layer { type: 'TanH' ... }")
"""
import json

import numpy as np

import mxnet_tpu as mx


def _import_caffe():
    try:
        import caffe  # noqa: F401
        return caffe
    except ImportError:
        raise ImportError(
            "plugin/caffe: executing a CaffePluginOp needs pycaffe "
            "(`import caffe`); the graph itself can be built and saved "
            "without it. Install caffe or provide a compatible module.")


@mx.operator.register("CaffePluginOp")
class CaffeOpProp(mx.operator.CustomOpProp):
    """prototxt: a caffe LayerParameter text block; num_out: outputs."""

    def __init__(self, prototxt="", num_out="1", num_weight="0"):
        super(CaffeOpProp, self).__init__(need_top_grad=True)
        self.prototxt = prototxt
        self.num_out = int(num_out)
        self.num_weight = int(num_weight)

    def list_arguments(self):
        args = ["data"]
        for i in range(self.num_weight):
            args.append("w%d" % i)
        return args

    def list_outputs(self):
        if self.num_out == 1:
            return ["output"]
        return ["output%d" % i for i in range(self.num_out)]

    def infer_shape(self, in_shape):
        caffe = _import_caffe()
        layer = caffe.make_layer(self.prototxt)
        out_shapes = layer.reshape([tuple(s) for s in in_shape])
        return in_shape, [tuple(s) for s in out_shapes], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        caffe = _import_caffe()
        layer = caffe.make_layer(self.prototxt)
        layer.reshape([tuple(s) for s in in_shapes])

        class CaffeOp(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                ins = [a.asnumpy() for a in in_data]
                outs = layer.forward(ins)
                if len(outs) != len(out_data):
                    raise ValueError(
                        "CaffePluginOp: layer returned %d outputs, "
                        "num_out declares %d" % (len(outs), len(out_data)))
                for i, (o_dst, o_src) in enumerate(zip(out_data, outs)):
                    self.assign(o_dst, req[i],
                                mx.nd.array(np.asarray(o_src, np.float32)))

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                gs = [g.asnumpy() for g in out_grad]
                ins = [a.asnumpy() for a in in_data]
                outs = [a.asnumpy() for a in out_data]
                dins = layer.backward(gs, ins, outs)
                for i, d in enumerate(dins):
                    self.assign(in_grad[i], req[i],
                                mx.nd.array(np.asarray(d, np.float32)))

        return CaffeOp()


def describe():
    """Plugin metadata (amalgamation/plugin registry surface)."""
    return json.dumps({
        "plugin": "caffe",
        "op_type": "CaffePluginOp",
        "requires": "pycaffe (import caffe)",
        "reference": "plugin/caffe/caffe_op.cc",
    })
